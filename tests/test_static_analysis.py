"""tl-lint static-analysis suite tests (analysis/dataflow.py,
analysis/regions.py, analysis/rules.py, analysis/checkers.py,
tools/lint.py; docs/static_analysis.md).

Layout:

- dataflow / region engine unit tests;
- per-rule golden fire/no-fire pairs, including the SEEDED MUTATION
  SWEEP: one known-good GEMM-shaped kernel, six mutations each injecting
  exactly one bug class, each asserted to fire its rule with the golden
  message while the clean kernel stays silent (the PR 5 chaos pattern
  applied to the front end);
- TL_TPU_LINT=0/warn/strict semantics and plan_desc/attrs/counters
  surfacing (goldens byte-stable when clean);
- golden-message tests for the four legacy checkers (TL101-TL104) and
  their aggregation into ONE SemanticError;
- CLI smoke over ops/gemm.py + ops/flash_attention.py and the
  CLI == in-pipeline consistency check.
"""

import json
import textwrap

import pytest

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T
from tilelang_mesh_tpu import observability as obs
from tilelang_mesh_tpu.analysis import (
    Diagnostic, SemanticError, collect_diagnostics, legacy_diagnostics,
    lint_mode, run_semantic_checks)
from tilelang_mesh_tpu.analysis import dataflow as df
from tilelang_mesh_tpu.analysis import regions as rg
from tilelang_mesh_tpu.ir import CopyStmt, FillStmt, GemmStmt, Var


def _rules(diags):
    return {d.rule for d in diags}


def _msgs(diags, rule):
    return [d.message for d in diags if d.rule == rule]


# ---------------------------------------------------------------------------
# dataflow engine unit tests
# ---------------------------------------------------------------------------


def _simple_kernel():
    @T.prim_func
    def k(A: T.Tensor((128, 128), "float32"),
          B: T.Tensor((128, 128), "float32")):
        with T.Kernel(1) as bx:
            A_s = T.alloc_shared((128, 128), "float32")
            acc = T.alloc_fragment((128, 128), "float32")
            T.copy(A[0, 0], A_s)
            T.clear(acc)
            for i, j in T.Parallel(128, 128):
                acc[i, j] = acc[i, j] + A_s[i, j]
            T.copy(acc, B[0, 0])
    return k.func


class TestDataflow:
    def test_stmt_accesses_copy(self):
        func = _simple_kernel()
        copies = [s for s, _ in df.iter_stmts(func.body)
                  if isinstance(s, CopyStmt)]
        acc = df.stmt_accesses(copies[0])
        kinds = [(a.kind, a.attr) for a in acc]
        assert ("read", "src") in kinds and ("write", "dst") in kinds

    def test_stmt_accesses_gemm_accum_reads_c(self):
        @T.prim_func
        def g(A: T.Tensor((128, 128), "float32")):
            with T.Kernel(1) as bx:
                s = T.alloc_shared((128, 128), "float32")
                c = T.alloc_fragment((128, 128), "float32")
                T.copy(A[0, 0], s)
                T.clear(c)
                T.gemm(s, s, c)                      # accumulating
                T.gemm(s, s, c, clear_accum=True)    # clearing
        gemms = [s for s, _ in df.iter_stmts(g.func.body)
                 if isinstance(s, GemmStmt)]
        accum = df.stmt_accesses(gemms[0])
        clear = df.stmt_accesses(gemms[1])
        assert ("read", "C") in [(a.kind, a.attr) for a in accum]
        assert ("read", "C") not in [(a.kind, a.attr) for a in clear]
        # reads are listed before the C write (init-order contract)
        c_events = [(a.kind) for a in accum if a.attr == "C"]
        assert c_events == ["read", "write"]

    def test_iter_stmts_reaches_else_branch(self):
        @T.prim_func
        def k(A: T.Tensor((8, 128), "float32")):
            with T.Kernel(1) as bx:
                s = T.alloc_shared((8, 128), "float32")
                with T.If(bx == 0):
                    T.fill(s, 1.0)
                with T.Else():
                    T.fill(s, 2.0)
        fills = [(s, ctx) for s, ctx in df.iter_stmts(k.func.body)
                 if isinstance(s, FillStmt)]
        assert len(fills) == 2
        # the else-arm fill carries a negative-polarity guard
        assert fills[1][1].guards[-1][1] is False

    def test_def_use_counts(self):
        func = _simple_kernel()
        du = df.def_use(func)
        by_name = {d.buffer.name: d for d in du.values()}
        assert len(by_name["shared"].writes) == 1    # the copy in
        assert len(by_name["shared"].reads) == 1     # the parallel read
        assert len(by_name["frag"].writes) == 2      # clear + store
        assert len(by_name["frag"].reads) == 2       # store value + copy

    def test_writes_in_and_scratch(self):
        func = _simple_kernel()
        scratch = df.scratch_buffers(func)
        assert {b.name for b in scratch.values()} == {"shared", "frag"}
        kn = func.kernel_node()
        assert df.writes_in(kn.body) >= set(scratch)


class TestRegions:
    def test_expr_interval(self):
        i, j = Var("i"), Var("j")
        r = rg.VarRanges()
        r.add(i, 0, 7)
        r.add(j, 0, 3)
        assert rg.expr_interval(i * 16 + j, r) == (0, 115)
        assert rg.expr_interval(8 - i, r) == (1, 8)
        assert rg.expr_interval(5, r) == (5, 5)
        k = Var("k")      # unranged var -> unknown
        assert rg.expr_interval(i + k, r) is None

    def test_access_affine_and_missing(self):
        i, j = Var("i"), Var("j")
        forms = rg.access_affine((i, 0), [i, j])
        assert forms is not None
        assert [v.name for v in rg.vars_missing_from(forms, [i, j])] \
            == ["j"]
        assert rg.vars_missing_from(rg.access_affine((i, j), [i, j]),
                                    [i, j]) == []

    def test_collision_shift(self):
        i = Var("i")
        w = rg.access_affine((i,), [i])
        r = rg.access_affine((i + 1,), [i])
        hit = rg.collision_shift(w, r, {id(i): 8})
        assert hit == (id(i), 1)
        # same-iteration access is not a collision
        assert rg.collision_shift(w, w, {id(i): 8}) is None
        # shift outside the extent is unreachable
        r9 = rg.access_affine((i + 9,), [i])
        assert rg.collision_shift(w, r9, {id(i): 8}) is None


# ---------------------------------------------------------------------------
# seeded mutation sweep: one clean kernel, six injected bug classes
# ---------------------------------------------------------------------------


def _mutant(mutate=None):
    """A known-good pipelined GEMM-shaped kernel; each mutation injects
    exactly one bug class."""
    par_n = 132 if mutate == "TL004" else 128

    @T.prim_func
    def k(A: T.Tensor((256, 256), "float32"),
          B: T.Tensor((256, 256), "float32"),
          C: T.Tensor((256, 256), "float32")):
        with T.Kernel(2, 2) as (bx, by):
            A_s = T.alloc_shared((128, 128), "float32")
            B_s = T.alloc_shared((128, 128), "float32")
            C_l = T.alloc_fragment((128, 128), "float32")
            if mutate == "TL006":
                T.alloc_fragment((128, 128), "float32")
            if mutate != "TL003":
                T.clear(C_l)
            for ko in T.Pipelined(2):
                T.copy(A[by * 128, ko * 128], A_s)
                T.copy(B[ko * 128, bx * 128], B_s)
                T.gemm(A_s, B_s, C_l, clear_accum=False)
            for i, j in T.Parallel(128, par_n):
                if mutate == "TL001":
                    C_l[0, j] = C_l[i, j] * 2.0
                else:
                    C_l[i, j] = C_l[i, j] * 2.0
            T.copy(C_l, C[by * 128, bx * 128])
    return k.func


class TestMutationSweep:
    def test_clean_kernel_is_silent(self):
        diags = collect_diagnostics(_mutant(None))
        assert diags == []

    def test_tl001_parallel_race_fires(self):
        diags = collect_diagnostics(_mutant("TL001"))
        assert "TL001" in _rules(diags)
        msg = _msgs(diags, "TL001")[0]
        assert "race" in msg and "C_l" not in msg or "frag" in msg

    def test_tl002_pipeline_hazard_fires(self):
        @T.prim_func
        def k(A: T.Tensor((256, 128), "float32"),
              B: T.Tensor((256, 128), "float32")):
            with T.Kernel(1) as bx:
                s = T.alloc_shared((128, 128), "float32")
                o = T.alloc_fragment((128, 128), "float32")
                sem = T.alloc_semaphore(2)
                T.copy_async(A[0, 0], s, sem, 0)
                for i, j in T.Parallel(128, 128):
                    o[i, j] = s[i, j]            # consumed before wait
                T.copy_wait(A[0, 0], s, sem, 0)
                T.copy(o, B[0, 0])
        diags = collect_diagnostics(k.func)
        assert "TL002" in _rules(diags)
        assert any("T.copy_wait" in m for m in _msgs(diags, "TL002"))

    def test_tl003_uninitialized_read_fires(self):
        diags = collect_diagnostics(_mutant("TL003"))
        assert "TL003" in _rules(diags)
        msg = _msgs(diags, "TL003")[0]
        assert "GemmStmt.C" in msg and "clear_accum" in msg

    def test_tl004_out_of_bounds_fires(self):
        diags = collect_diagnostics(_mutant("TL004"))
        assert "TL004" in _rules(diags)
        assert any("walks outside" in m for m in _msgs(diags, "TL004"))

    def test_tl005_vmem_budget_fires(self):
        diags = collect_diagnostics(
            _mutant(None), {"tl.tpu.vmem_budget_bytes": 4096})
        assert "TL005" in _rules(diags)
        msg = _msgs(diags, "TL005")[0]
        assert "exceeds" in msg and "largest consumers" in msg

    def test_tl006_dead_store_fires(self):
        diags = collect_diagnostics(_mutant("TL006"))
        assert "TL006" in _rules(diags)
        assert any("never used" in m for m in _msgs(diags, "TL006"))


# ---------------------------------------------------------------------------
# per-rule precision (no-fire on the idioms the ops library uses)
# ---------------------------------------------------------------------------


class TestTL001Precision:
    def test_elementwise_update_is_clean(self):
        assert collect_diagnostics(_simple_kernel()) == []

    def test_idempotent_broadcast_store_is_warning(self):
        @T.prim_func
        def k(A: T.Tensor((128, 128), "float32")):
            with T.Kernel(1) as bx:
                s = T.alloc_shared((128, 128), "float32")
                v = T.alloc_fragment((1,), "float32")
                T.copy(A[0, 0], s)
                T.fill(v, 0.0)
                for i in T.Parallel(128):
                    v[0] = 7.0           # same value every iteration
                s[0, 0] = v[0]
        diags = [d for d in collect_diagnostics(k.func)
                 if d.rule == "TL001"]
        assert len(diags) == 1 and diags[0].severity == "warning"
        assert "idempotent" in diags[0].message

    def test_value_dependent_broadcast_is_error(self):
        @T.prim_func
        def k(A: T.Tensor((128, 128), "float32")):
            with T.Kernel(1) as bx:
                s = T.alloc_shared((128, 128), "float32")
                v = T.alloc_fragment((1,), "float32")
                T.copy(A[0, 0], s)
                T.fill(v, 0.0)
                for i, j in T.Parallel(128, 128):
                    v[0] = v[0] + s[i, j]     # lost-update reduction
                s[0, 0] = v[0]
        diags = [d for d in collect_diagnostics(k.func)
                 if d.rule == "TL001"]
        assert diags and diags[0].severity == "error"
        assert diags[0].buffer == "frag"

    def test_shifted_read_fires(self):
        @T.prim_func
        def k(A: T.Tensor((128, 128), "float32")):
            with T.Kernel(1) as bx:
                s = T.alloc_shared((128, 128), "float32")
                T.copy(A[0, 0], s)
                for i, j in T.Parallel(127, 128):
                    s[i, j] = s[i + 1, j]     # cross-iteration shift
        diags = [d for d in collect_diagnostics(k.func)
                 if d.rule == "TL001"]
        assert diags and "read-write race" in diags[0].message
        # iteration i writes s[i], which iteration i-1 READS (as s[i])
        assert "iteration i-1 reads" in diags[0].message

    def test_sibling_of_nested_parallel_not_charged(self):
        """Review regression: a store that is a SIBLING of a nested
        T.Parallel must not be judged over that loop's vars."""
        @T.prim_func
        def k(A: T.Tensor((128, 128), "float32"),
              B: T.Tensor((128, 128), "float32")):
            with T.Kernel(1) as bx:
                s = T.alloc_shared((128, 128), "float32")
                row = T.alloc_fragment((128,), "float32")
                T.copy(A[0, 0], s)
                for i in T.Parallel(128):
                    row[i] = s[i, 0]        # uses i: fine
                    for j in T.Parallel(128):
                        s[i, j] = s[i, j] + 1.0   # uses i and j: fine
                T.copy(s, B[0, 0])
        assert "TL001" not in _rules(collect_diagnostics(k.func))

    def test_atomic_add_in_parallel_is_clean(self):
        @T.prim_func
        def k(A: T.Tensor((128, 128), "float32"),
              O: T.Tensor((128, 128), "float32")):
            with T.Kernel(1) as bx:
                s = T.alloc_shared((128, 128), "float32")
                T.copy(A[0, 0], s)
                for i, j in T.Parallel(128, 128):
                    T.atomic_add(O[i, j], s[i, j])
        assert "TL001" not in _rules(collect_diagnostics(k.func))


class TestTL002Precision:
    def test_double_buffered_pipeline_is_clean(self):
        """The examples/warp_specialize split-phase DMA schedule: start
        one slab ahead, wait right before the gemm — no hazard."""
        nstep = 4

        @T.prim_func
        def k(A: T.Tensor((128, 512), "float32"),
              C: T.Tensor((128, 512), "float32")):
            with T.Kernel(1) as bx:
                s = T.alloc_shared((2, 128, 128), "float32")
                sem = T.alloc_semaphore(2)
                T.copy_async(A[0, 0], s[0, 0:128, 0:128], sem, 0)
                for ko in range(nstep):
                    cur, nxt = ko % 2, (ko + 1) % 2
                    if ko + 1 < nstep:
                        T.copy_async(A[0, (ko + 1) * 128],
                                     s[nxt, 0:128, 0:128], sem, nxt)
                    T.copy_wait(A[0, ko * 128],
                                s[cur, 0:128, 0:128], sem, cur)
                    T.copy(s[cur, 0:128, 0:128],
                           C[0:128, ko * 128:(ko + 1) * 128])
        diags = collect_diagnostics(k.func)
        assert "TL002" not in _rules(diags)

    def test_slot_reuse_fires(self):
        @T.prim_func
        def k(A: T.Tensor((256, 128), "float32"),
              B: T.Tensor((256, 128), "float32")):
            with T.Kernel(1) as bx:
                s = T.alloc_shared((2, 128, 128), "float32")
                sem = T.alloc_semaphore(2)
                T.copy_async(A[0, 0], s[0, 0:128, 0:128], sem, 0)
                T.copy_async(A[128, 0], s[1, 0:128, 0:128], sem, 0)
                T.copy_wait(A[0, 0], s[0, 0:128, 0:128], sem, 0)
                T.copy(s[0, 0:128, 0:128], B[0:128, 0:128])
        diags = collect_diagnostics(k.func)
        assert any("re-armed" in m for m in _msgs(diags, "TL002"))

    def test_extent_one_loop_has_no_back_edge(self):
        """Review regression: a loop whose every static extent is 1 has
        no second iteration, so the loop-carried reuse scan must not
        model one (no false slot-reuse on nK=1 pipelines)."""
        @T.prim_func
        def k(A: T.Tensor((128, 128), "float32"),
              B: T.Tensor((128, 128), "float32")):
            with T.Kernel(1) as bx:
                s = T.alloc_shared((128, 128), "float32")
                sem = T.alloc_semaphore(1)
                for ko in T.serial(1):
                    T.copy_async(A[0, 0], s, sem, 0)
                T.copy_wait(A[0, 0], s, sem, 0)
                T.copy(s, B[0, 0])
        assert "TL002" not in _rules(collect_diagnostics(k.func))

    def test_dynamic_slot_wait_covers_never_awaited(self):
        """Review regression: a T.copy_wait with a dynamic slot expr
        (ko % 2) must count as awaiting every slot of its semaphore."""
        @T.prim_func
        def k(A: T.Tensor((256, 128), "float32"),
              B: T.Tensor((128, 128), "float32")):
            with T.Kernel(1) as bx:
                s = T.alloc_shared((128, 128), "float32")
                sem = T.alloc_semaphore(2)
                T.copy_async(A[0, 0], s, sem, 0)
                for ko in T.serial(2):
                    T.copy_wait(A[0, 0], s, sem, ko % 2)
                T.copy(s, B[0, 0])
        assert "TL002" not in _rules(collect_diagnostics(k.func))

    def test_never_awaited_is_warning(self):
        @T.prim_func
        def k(A: T.Tensor((128, 128), "float32"),
              B: T.Tensor((128, 128), "float32")):
            with T.Kernel(1) as bx:
                s = T.alloc_shared((128, 128), "float32")
                sem = T.alloc_semaphore(1)
                T.copy_async(A[0, 0], s, sem, 0)
                T.copy(A[0, 0], B[0, 0])
        diags = [d for d in collect_diagnostics(k.func)
                 if d.rule == "TL002"]
        assert diags and diags[0].severity == "warning"
        assert "never awaited" in diags[0].message


class TestTL003Precision:
    def test_guarded_first_iteration_init_is_clean(self):
        """The flash-attention idiom: state filled under If(ko == 0)."""
        @T.prim_func
        def k(A: T.Tensor((256, 128), "float32"),
              B: T.Tensor((128, 128), "float32")):
            with T.Kernel(1) as bx:
                s = T.alloc_shared((128, 128), "float32")
                acc = T.alloc_fragment((128, 128), "float32")
                for ko in T.Pipelined(2):
                    with T.If(ko == 0):
                        T.fill(acc, 0.0)
                    T.copy(A[ko * 128, 0], s)
                    for i, j in T.Parallel(128, 128):
                        acc[i, j] = acc[i, j] + s[i, j]
                T.copy(acc, B[0, 0])
        assert "TL003" not in _rules(collect_diagnostics(k.func))

    def test_loop_carried_read_behind_guard_is_clean(self):
        """Software-pipeline idiom: If(ko > 0) guards the read of a
        value the previous iteration wrote."""
        @T.prim_func
        def k(A: T.Tensor((256, 128), "float32"),
              B: T.Tensor((128, 128), "float32")):
            with T.Kernel(1) as bx:
                prev = T.alloc_fragment((128, 128), "float32")
                out = T.alloc_fragment((128, 128), "float32")
                T.fill(out, 0.0)
                for ko in T.Pipelined(2):
                    with T.If(ko > 0):
                        for i, j in T.Parallel(128, 128):
                            out[i, j] = out[i, j] + prev[i, j]
                    T.copy(A[ko * 128, 0], prev)
                T.copy(out, B[0, 0])
        assert "TL003" not in _rules(collect_diagnostics(k.func))

    def test_read_in_else_branch_fires(self):
        """The traversal-gap regression: an uninitialized read hiding in
        a T.Else body must be reachable by the analysis."""
        @T.prim_func
        def k(A: T.Tensor((128, 128), "float32"),
              B: T.Tensor((128, 128), "float32")):
            with T.Kernel(2) as bx:
                s = T.alloc_shared((128, 128), "float32")
                with T.If(bx == 0):
                    T.copy(A[0, 0], s)
                    T.copy(s, B[0, 0])
                with T.Else():
                    T.copy(s, B[0, 0])     # s never written on this path
        diags = collect_diagnostics(k.func)
        assert "TL003" in _rules(diags)

    def test_partial_then_branch_init_is_maybe_not_flagged(self):
        @T.prim_func
        def k(A: T.Tensor((128, 128), "float32"),
              B: T.Tensor((128, 128), "float32")):
            with T.Kernel(2) as bx:
                s = T.alloc_shared((128, 128), "float32")
                with T.If(bx == 0):
                    T.copy(A[0, 0], s)
                T.copy(s, B[0, 0])     # maybe-initialized: not flagged
        assert "TL003" not in _rules(collect_diagnostics(k.func))


class TestTL004Precision:
    def test_guarded_ragged_access_is_clean(self):
        @T.prim_func
        def k(A: T.Tensor((100, 128), "float32"),
              B: T.Tensor((128, 128), "float32")):
            with T.Kernel(1) as bx:
                s = T.alloc_shared((100, 128), "float32")
                T.copy(A[0, 0], s)
                for i, j in T.Parallel(128, 128):
                    with T.If(i < 100):
                        B[i, j] = s[i, j]
        assert "TL004" not in _rules(collect_diagnostics(k.func))

    def test_global_oob_is_warning_onchip_is_error(self):
        @T.prim_func
        def k(A: T.Tensor((128, 128), "float32"),
              B: T.Tensor((200, 128), "float32")):
            with T.Kernel(1) as bx:
                s = T.alloc_shared((48, 128), "float32")
                for ko in T.serial(3):
                    T.copy(A[ko * 48, 0], s)     # 3*48=144 > 128: global
                    T.copy(s, B[ko * 48, 0])
        diags = [d for d in collect_diagnostics(k.func)
                 if d.rule == "TL004"]
        assert diags and all(d.severity == "warning" for d in diags)

        @T.prim_func
        def k2(A: T.Tensor((256, 128), "float32"),
               B: T.Tensor((256, 128), "float32")):
            with T.Kernel(1) as bx:
                s = T.alloc_shared((100, 128), "float32")
                T.copy(A[0, 0], s[0:100, 0:128])
                for i, j in T.Parallel(128, 128):
                    B[i, j] = s[i, j]            # 128 > 100 rows: VMEM
        diags2 = [d for d in collect_diagnostics(k2.func)
                  if d.rule == "TL004"]
        assert diags2 and any(d.severity == "error" for d in diags2)


# ---------------------------------------------------------------------------
# legacy checkers: golden messages + aggregation (TL100-TL104)
# ---------------------------------------------------------------------------


class TestLegacyCheckers:
    def test_tl101_async_copy_in_parallel_fires(self):
        """The traversal-gap fix: split-phase DMA inside T.Parallel was
        previously invisible to the nested-loop checker."""
        @T.prim_func
        def k(A: T.Tensor((128, 128), "float32")):
            with T.Kernel(1) as bx:
                s = T.alloc_shared((128, 128), "float32")
                sem = T.alloc_semaphore(1)
                for i in T.Parallel(128):
                    T.copy_async(A[0, 0], s, sem, 0)
        diags = legacy_diagnostics(k.func)
        assert any(d.rule == "TL101" and "AsyncCopyStmt" in d.message
                   for d in diags)

    def test_tl101_golden_message(self):
        @T.prim_func
        def k(A: T.Tensor((128, 128), "float32"),
              B: T.Tensor((128, 128), "float32")):
            with T.Kernel(1) as bx:
                s = T.alloc_shared((128, 128), "float32")
                for i in T.Parallel(128):
                    T.copy(A[0, 0], s)
        msgs = [d.message for d in legacy_diagnostics(k.func)
                if d.rule == "TL101"]
        assert msgs == ["tile op CopyStmt inside T.Parallel; hoist it "
                        "out of the elementwise loop"]

    def test_tl103_golden_message_and_loc(self):
        @T.prim_func
        def k(A: T.Tensor((16, 128), "float32")):
            with T.Kernel(1) as bx:
                s = T.alloc_shared((16, 128), "float32")
                T.copy(A[4:20, 0:128], s)   # rows [4:20) exceed 16
        diags = [d for d in legacy_diagnostics(k.func)
                 if d.rule == "TL103"]
        assert diags
        assert "window [4:20) exceeds A dim 0 (extent 16)" \
            in diags[0].message
        assert diags[0].loc and "test_static_analysis.py" in diags[0].loc

    def test_aggregation_one_error_reports_all(self):
        """Findings from DIFFERENT checkers land in one SemanticError."""
        @T.prim_func
        def k(A: T.Tensor((16, 128), "float32")):
            with T.Kernel(1) as bx:
                s = T.alloc_shared((16, 128), "float32")
                T.copy(A[4:20, 0:128], s)       # TL103 bounds
                for i in T.Parallel(16):
                    T.copy(A[0, 0], s)          # TL101 tile op
        with pytest.raises(SemanticError) as ei:
            run_semantic_checks(k.func)
        text = str(ei.value)
        assert "TL101" in text and "TL103" in text
        assert {d.rule for d in ei.value.diagnostics} == {"TL101",
                                                          "TL103"}


# ---------------------------------------------------------------------------
# TL_TPU_LINT knob + surfacing
# ---------------------------------------------------------------------------


def _racy_func():
    """Lints with a TL001 error; the race also trips codegen, so only
    strict mode (which raises BEFORE codegen) lowers this one."""
    @T.prim_func
    def racy(A: T.Tensor((128, 128), "float32"),
             B: T.Tensor((128, 128), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_shared((128, 128), "float32")
            v = T.alloc_fragment((1,), "float32")
            T.copy(A[0, 0], s)
            T.fill(v, 0.0)
            for i, j in T.Parallel(128, 128):
                v[0] = v[0] + s[i, j]
            s[0, 0] = v[0]
            T.copy(s, B[0, 0])
    return racy


def _dirty_compilable():
    """Lints dirty (TL003 error + TL006 info) but codegens fine — the
    kernel the warn-mode surfacing tests lower end to end."""
    @T.prim_func
    def dirty(A: T.Tensor((128, 128), "float32"),
              B: T.Tensor((128, 128), "float32")):
        with T.Kernel(2) as bx:
            s = T.alloc_shared((128, 128), "float32")
            dead = T.alloc_fragment((8, 128), "float32")
            T.fill(dead, 0.0)                  # TL006: never read
            with T.If(bx == 0):
                T.copy(A[0, 0], s)
                T.copy(s, B[0, 0])
            with T.Else():
                T.copy(s, B[0, 0])             # TL003: uninit path
    return dirty


class TestLintKnob:
    def test_mode_parsing(self, monkeypatch):
        monkeypatch.setenv("TL_TPU_LINT", "warn")
        assert lint_mode() == "warn"
        monkeypatch.setenv("TL_TPU_LINT", "0")
        assert lint_mode() == "off"
        monkeypatch.setenv("TL_TPU_LINT", "strict")
        assert lint_mode() == "strict"
        assert lint_mode({"tl.tpu.lint": "off"}) == "off"
        monkeypatch.setenv("TL_TPU_LINT", "bogus")
        with pytest.raises(ValueError, match="TL_TPU_LINT"):
            lint_mode()

    def test_warn_mode_compiles_and_surfaces(self, monkeypatch):
        monkeypatch.setenv("TL_TPU_LINT", "warn")
        # tile-opt's dse would auto-fix (and consume) the TL006 finding;
        # this test asserts the raw lint surface
        monkeypatch.setenv("TL_TPU_TILE_OPT", "0")
        art = tilelang.lower(_dirty_compilable())
        lint = art.attrs.get("lint")
        assert lint and {d["rule"] for d in lint} == {"TL003", "TL006"}
        assert "lint[warn]" in art.plan_desc
        assert "TL003" in art.plan_desc

    def test_off_mode_adds_nothing(self, monkeypatch):
        monkeypatch.setenv("TL_TPU_LINT", "0")
        art = tilelang.lower(_dirty_compilable())
        assert "lint" not in art.attrs
        assert "lint[" not in art.plan_desc

    def test_strict_mode_raises(self, monkeypatch):
        monkeypatch.setenv("TL_TPU_LINT", "strict")
        with pytest.raises(SemanticError, match="TL001"):
            tilelang.lower(_racy_func())

    def test_clean_plan_desc_byte_stable(self, monkeypatch):
        from tilelang_mesh_tpu.ops.gemm import matmul_kernel
        monkeypatch.setenv("TL_TPU_LINT", "0")
        matmul_kernel.cache_clear()
        off = matmul_kernel(256, 256, 256, 128, 128, 128) \
            .artifact.plan_desc
        monkeypatch.setenv("TL_TPU_LINT", "warn")
        matmul_kernel.cache_clear()
        warn = matmul_kernel(256, 256, 256, 128, 128, 128) \
            .artifact.plan_desc
        assert off == warn
        assert "lint[" not in warn

    def test_counters_and_metrics_summary(self, monkeypatch):
        obs.reset()
        monkeypatch.setenv("TL_TPU_LINT", "warn")
        monkeypatch.setenv("TL_TPU_TILE_OPT", "0")   # keep TL006 surfaced
        tilelang.lower(_dirty_compilable())
        summary = obs.metrics_summary()["lint"]
        assert summary["findings"] >= 2
        assert summary["errors"] >= 1
        assert "TL003" in summary["by_rule"]
        c = obs.get_tracer().counters()
        assert any(k.startswith("lint.findings{rule=TL003")
                   for k in c)

    def test_cache_does_not_bypass_strict(self, monkeypatch):
        """Review regression: the lint mode is part of the kernel-cache
        key, so a warn-mode cached artifact cannot satisfy a strict
        compile (which must re-check and reject)."""
        monkeypatch.setenv("TL_TPU_LINT", "warn")
        f = _dirty_compilable()
        tilelang.compile(f)                      # cached under warn
        monkeypatch.setenv("TL_TPU_LINT", "strict")
        with pytest.raises(SemanticError, match="TL003"):
            tilelang.compile(f)

    def test_strict_clean_kernel_still_compiles(self, monkeypatch):
        monkeypatch.setenv("TL_TPU_LINT", "strict")
        art = tilelang.lower(_simple_kernel())
        assert "lint[" not in art.plan_desc

    def test_source_loc_points_at_kernel_line(self):
        diags = [d for d in collect_diagnostics(_racy_func().func)
                 if d.rule == "TL001"]
        assert diags and diags[0].loc
        assert "test_static_analysis.py" in diags[0].loc


class TestMeshSurfacing:
    def test_mesh_lint_block_and_attrs(self, monkeypatch):
        monkeypatch.setenv("TL_TPU_LINT", "warn")
        # with comm_opt dce enabled TL006 stays silent on dead
        # collective results (the optimizer deletes them); disable the
        # rewrite so the mesh lint SURFACE is what's under test
        monkeypatch.setenv("TL_TPU_COMM_OPT", "0")
        from tilelang_mesh_tpu.parallel import mesh_config
        with mesh_config(2, 2):
            @T.prim_func
            def k(A: T.MeshTensor((32, 128),
                                  T.MeshShardingPolicy(cross_mesh_dim=0),
                                  (2, 2), "float32"),
                  B: T.MeshTensor((32, 128),
                                  T.MeshShardingPolicy(cross_mesh_dim=0),
                                  (2, 2), "float32")):
                with T.Kernel(1) as bx:
                    x = T.alloc_fragment((8, 128), "float32")
                    dead = T.alloc_fragment((8, 1), "float32")
                    T.copy(A, x)
                    T.comm.all_reduce(x, dead, "sum", "v", dim=1)
                    T.copy(x, B)
        art = tilelang.lower(k, target="cpu-mesh[2x2]")
        assert art.attrs["lint"] and \
            art.attrs["lint"][0]["rule"] == "TL006"
        assert "lint[warn]" in art.plan_desc

    def test_mesh_clean_program_adds_nothing(self, monkeypatch):
        monkeypatch.setenv("TL_TPU_LINT", "warn")
        from tilelang_mesh_tpu.parallel import mesh_config
        with mesh_config(2, 2):
            @T.prim_func
            def k(A: T.MeshTensor((32, 128),
                                  T.MeshShardingPolicy(cross_mesh_dim=0),
                                  (2, 2), "float32"),
                  B: T.MeshTensor((32, 128),
                                  T.MeshShardingPolicy(cross_mesh_dim=0),
                                  (2, 2), "float32")):
                with T.Kernel(1) as bx:
                    x = T.alloc_fragment((8, 128), "float32")
                    T.copy(A, x)
                    T.copy(x, B)
        art = tilelang.lower(k, target="cpu-mesh[2x2]")
        assert art.attrs["lint"] is None
        assert "lint[" not in art.plan_desc


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


class TestCLI:
    def test_cli_smoke_over_oplib_modules(self):
        from tilelang_mesh_tpu.tools.lint import lint_targets
        report = lint_targets(["tilelang_mesh_tpu/ops/gemm.py",
                               "tilelang_mesh_tpu/ops/flash_attention.py"])
        assert report["kernels_linted"] >= 2
        assert report["summary"]["errors"] == 0

    def test_cli_main_json_and_exit_codes(self, tmp_path, capsys):
        from tilelang_mesh_tpu.tools import lint as lint_cli
        mod = tmp_path / "clean_mod.py"
        mod.write_text(textwrap.dedent("""\
            import tilelang_mesh_tpu.language as T

            @T.prim_func
            def ok(A: T.Tensor((128, 128), "float32"),
                   B: T.Tensor((128, 128), "float32")):
                with T.Kernel(1) as bx:
                    s = T.alloc_shared((128, 128), "float32")
                    T.copy(A[0, 0], s)
                    T.copy(s, B[0, 0])
        """))
        rc = lint_cli.main([str(mod), "--json"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 0 and out["kernels_linted"] == 1
        assert out["summary"]["errors"] == 0

        bad = tmp_path / "racy_mod.py"
        bad.write_text(textwrap.dedent("""\
            import tilelang_mesh_tpu.language as T

            @T.prim_func
            def racy(A: T.Tensor((128, 128), "float32"),
                     B: T.Tensor((128, 128), "float32")):
                with T.Kernel(1) as bx:
                    s = T.alloc_shared((128, 128), "float32")
                    v = T.alloc_fragment((1,), "float32")
                    T.copy(A[0, 0], s)
                    T.fill(v, 0.0)
                    for i, j in T.Parallel(128, 128):
                        v[0] = v[0] + s[i, j]
                    s[0, 0] = v[0]
                    T.copy(s, B[0, 0])
        """))
        outfile = tmp_path / "report.json"
        rc = lint_cli.main([str(bad), "--out", str(outfile)])
        capsys.readouterr()
        assert rc == 1
        saved = json.loads(outfile.read_text())
        assert saved["summary"]["errors"] >= 1
        assert any(f["rule"] == "TL001" for f in saved["findings"])

    def test_cli_matches_pipeline_findings(self):
        """The CLI and the in-pipeline pass agree on the same kernel."""
        func = _racy_func().func
        cli_view = collect_diagnostics(func, with_plan=True)
        pipeline_view = run_semantic_checks(func)   # warn mode default
        from tilelang_mesh_tpu.analysis import run_plan_lint
        from tilelang_mesh_tpu.transform.plan import plan_kernel
        pipeline_view = list(pipeline_view) + \
            run_plan_lint(func, plan_kernel(func, {}))
        assert sorted((d.rule, d.message) for d in cli_view) == \
            sorted((d.rule, d.message) for d in pipeline_view)

    def test_analyzer_lint_subcommand(self, capsys):
        from tilelang_mesh_tpu.tools.analyzer import main as analyzer_main
        rc = analyzer_main(["lint", "tilelang_mesh_tpu/ops/gemm.py",
                            "--json"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert out["kernels_linted"] >= 1

    def test_diagnostic_round_trip(self):
        d = Diagnostic("TL001", "error", "msg", kernel="k",
                       buffer="b", op="CopyStmt", loc="f.py:3")
        assert Diagnostic.from_dict(d.to_dict()) == d
