"""Execution + numerics tests for GEMM kernels (SURVEY §4 style 2;
reference testing/python/kernel/test_tilelang_kernel_gemm.py).

Run in Pallas interpret mode on CPU (which emulates TPU MXU bf16 numerics),
or on real TPU with TL_TPU_TEST_DEVICE=tpu.
"""

import numpy as np
import pytest

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T
from tilelang_mesh_tpu.utils.tensor import assert_allclose


def matmul_kernel(M, N, K, bm, bn, bk, trans_A=False, trans_B=False,
                  in_dtype="float32", accum_dtype="float32"):
    a_shape = (K, M) if trans_A else (M, K)
    b_shape = (N, K) if trans_B else (K, N)
    a_tile = (bk, bm) if trans_A else (bm, bk)
    b_tile = (bn, bk) if trans_B else (bk, bn)

    @T.prim_func
    def main(A: T.Tensor(a_shape, in_dtype),
             B: T.Tensor(b_shape, in_dtype),
             C: T.Tensor((M, N), in_dtype)):
        with T.Kernel(T.ceildiv(N, bn), T.ceildiv(M, bm)) as (bx, by):
            A_s = T.alloc_shared(a_tile, in_dtype)
            B_s = T.alloc_shared(b_tile, in_dtype)
            C_l = T.alloc_fragment((bm, bn), accum_dtype)
            T.clear(C_l)
            for ko in T.Pipelined(T.ceildiv(K, bk), num_stages=2):
                if trans_A:
                    T.copy(A[ko * bk, by * bm], A_s)
                else:
                    T.copy(A[by * bm, ko * bk], A_s)
                if trans_B:
                    T.copy(B[bx * bn, ko * bk], B_s)
                else:
                    T.copy(B[ko * bk, bx * bn], B_s)
                T.gemm(A_s, B_s, C_l, transpose_A=trans_A,
                       transpose_B=trans_B)
            T.copy(C_l, C[by * bm, bx * bn])
    return main


def _ref(a, b, trans_A, trans_B):
    a = a.T if trans_A else a
    b = b.T if trans_B else b
    return (a.astype(np.float32) @ b.astype(np.float32))


@pytest.mark.parametrize("trans_A,trans_B", [(False, False), (False, True),
                                             (True, False), (True, True)])
def test_gemm_transposes(trans_A, trans_B):
    M = N = K = 256
    k = tilelang.compile(matmul_kernel(M, N, K, 128, 128, 64, trans_A,
                                       trans_B))
    rng = np.random.default_rng(0)
    a = rng.standard_normal((K, M) if trans_A else (M, K),
                            dtype=np.float32)
    b = rng.standard_normal((N, K) if trans_B else (K, N),
                            dtype=np.float32)
    c = k(a, b)
    assert_allclose(c, _ref(a, b, trans_A, trans_B), rtol=2e-2, atol=2e-2)


def test_gemm_bf16_accum_f32():
    import jax.numpy as jnp
    M = N = K = 256
    k = tilelang.compile(matmul_kernel(M, N, K, 128, 128, 128,
                                       in_dtype="bfloat16",
                                       accum_dtype="float32"))
    rng = np.random.default_rng(1)
    a = jnp.asarray(rng.standard_normal((M, K)), jnp.bfloat16)
    b = jnp.asarray(rng.standard_normal((K, N)), jnp.bfloat16)
    c = k(a, b)
    ref = np.asarray(a, np.float32) @ np.asarray(b, np.float32)
    assert_allclose(np.asarray(c, np.float32), ref, rtol=5e-2, atol=5e-1)


def test_gemm_clear_accum():
    M = N = K = 128

    @T.prim_func
    def main(A: T.Tensor((M, K), "float32"),
             B: T.Tensor((K, N), "float32"),
             C: T.Tensor((M, N), "float32")):
        with T.Kernel(1, 1) as (bx, by):
            A_s = T.alloc_shared((M, K), "float32")
            B_s = T.alloc_shared((K, N), "float32")
            C_l = T.alloc_fragment((M, N), "float32")
            T.copy(A, A_s)
            T.copy(B, B_s)
            # garbage in accumulator, clear_accum must overwrite
            T.fill(C_l, 123.0)
            T.gemm(A_s, B_s, C_l, clear_accum=True)
            T.copy(C_l, C)

    k = tilelang.compile(main)
    rng = np.random.default_rng(2)
    a = rng.standard_normal((M, K), dtype=np.float32)
    b = rng.standard_normal((K, N), dtype=np.float32)
    assert_allclose(k(a, b), a @ b, rtol=2e-2, atol=2e-2)


def test_reference_style_call_with_output_arg():
    """Reference call convention kernel(a, b, c) with c a numpy output."""
    M = N = K = 128
    k = tilelang.compile(matmul_kernel(M, N, K, 128, 128, 64))
    rng = np.random.default_rng(3)
    a = rng.standard_normal((M, K), dtype=np.float32)
    b = rng.standard_normal((K, N), dtype=np.float32)
    c = np.empty((M, N), dtype=np.float32)
    k(a, b, c)
    assert_allclose(c, a @ b, rtol=2e-2, atol=2e-2)


def test_profiler_assert_allclose_and_bench():
    M = N = K = 128
    k = tilelang.compile(matmul_kernel(M, N, K, 128, 128, 128))
    prof = k.get_profiler()
    import jax.numpy as jnp
    prof.assert_allclose(
        lambda a, b: jnp.dot(a, b, preferred_element_type=jnp.float32),
        rtol=2e-2, atol=2e-2)
    lat = prof.do_bench(warmup=1, rep=2, backend="wall")
    assert lat > 0


def test_kernel_source_inspectable():
    k = tilelang.compile(matmul_kernel(128, 128, 128, 128, 128, 64))
    src = k.get_kernel_source()
    assert "pl.pallas_call" in src
    assert "dot_general" in src
    assert "BlockSpec" in src
