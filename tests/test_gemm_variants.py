"""Split-K / stream-K / GEMV / block-sparse GEMM vs dense references."""

import numpy as np
import pytest

import jax.numpy as jnp

from tilelang_mesh_tpu.ops.gemm_variants import (
    matmul_splitk, matmul_streamk, gemv, blocksparse_matmul,
    _streamk_segments)
from tilelang_mesh_tpu.utils.tensor import assert_allclose


def _rand(shape, seed, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.standard_normal(shape) * 0.1, dtype)


def test_splitk_matches_dense():
    M, N, K = 256, 256, 1024
    a, b = _rand((M, K), 0), _rand((K, N), 1)
    out = matmul_splitk(a, b, n_split=4, block_M=128, block_N=128,
                        block_K=128, out_dtype="float32")
    assert_allclose(np.asarray(out), np.asarray(a) @ np.asarray(b),
                    rtol=1e-4, atol=1e-4)


def test_splitk_uneven_split_falls_back():
    M, N, K = 128, 128, 384
    a, b = _rand((M, K), 2), _rand((K, N), 3)
    out = matmul_splitk(a, b, n_split=5, block_M=128, block_N=128,
                        block_K=128, out_dtype="float32")
    assert_allclose(np.asarray(out), np.asarray(a) @ np.asarray(b),
                    rtol=1e-4, atol=1e-4)


def test_streamk_segments_cover_exactly():
    segs = _streamk_segments(n_tiles=7, k_iters=5, n_programs=4)
    seen = set()
    for tile, k0, k_len in segs:
        for k in range(k0, k0 + k_len):
            assert (tile, k) not in seen
            seen.add((tile, k))
    assert len(seen) == 7 * 5
    # balanced: no program-sized segment exceeds ceil(total/P)
    assert max(s[2] for s in segs) <= -(-7 * 5 // 4)


def test_streamk_matches_dense():
    M, N, K = 256, 384, 512
    a, b = _rand((M, K), 4), _rand((K, N), 5)
    out = matmul_streamk(a, b, n_programs=6, block_M=128, block_N=128,
                         block_K=128, out_dtype="float32")
    assert_allclose(np.asarray(out), np.asarray(a) @ np.asarray(b),
                    rtol=1e-4, atol=1e-4)


def test_gemv_matches_dense():
    N, K = 384, 512
    a = _rand((K,), 6)
    b = _rand((N, K), 7)
    out = gemv(a, b, out_dtype="float32")
    assert out.shape == (N,)
    assert_allclose(np.asarray(out), np.asarray(b) @ np.asarray(a),
                    rtol=1e-4, atol=1e-4)


def test_blocksparse_gemm():
    M, N, K, bm, bn = 256, 256, 256, 128, 128
    a, b = _rand((M, K), 8), _rand((K, N), 9)
    rng = np.random.default_rng(10)
    mask = jnp.asarray(rng.integers(0, 2, (M // bm, N // bn)), jnp.int32)
    out = np.asarray(blocksparse_matmul(a, b, mask, block_M=bm, block_N=bn,
                                        out_dtype="float32"))
    ref = np.asarray(a) @ np.asarray(b)
    dense_mask = np.kron(np.asarray(mask), np.ones((bm, bn))) != 0
    assert_allclose(out[dense_mask], ref[dense_mask], rtol=1e-4, atol=1e-4)
    assert np.abs(out[~dense_mask]).max() == 0.0


def test_varlen_grouped_gemm():
    import jax.numpy as jnp
    from tilelang_mesh_tpu.ops.grouped_gemm import (
        varlen_grouped_matmul, varlen_grouped_matmul_reference)
    rng = np.random.default_rng(3)
    sizes = (130, 0, 64, 257)
    K, N = 128, 128
    a = jnp.asarray(rng.standard_normal((sum(sizes), K)), jnp.float32)
    b = jnp.asarray(rng.standard_normal((len(sizes), K, N)), jnp.float32)
    out = varlen_grouped_matmul(a, b, sizes)
    ref = varlen_grouped_matmul_reference(a, b, sizes)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-2, atol=1e-1)
    # trans_b path with rectangular (block_N != block_K) tiles
    bt = jnp.transpose(b, (0, 2, 1))
    out_t = varlen_grouped_matmul(a, bt, sizes, trans_b=True,
                                  block_N=128, block_K=64)
    np.testing.assert_allclose(np.asarray(out_t), np.asarray(ref),
                               rtol=1e-2, atol=1e-1)


def test_varlen_grouped_gemm_validates():
    import jax.numpy as jnp
    from tilelang_mesh_tpu.ops.grouped_gemm import varlen_grouped_matmul
    a = jnp.zeros((10, 32), jnp.float32)
    b = jnp.zeros((2, 32, 32), jnp.float32)
    with pytest.raises(ValueError, match="sum"):
        varlen_grouped_matmul(a, b, (4, 4))
