"""tl-mesh-scope tests (observability/meshscope.py; docs/observability.md
"Mesh communication").

Covers the PR 18 tentpole: the route model's per-collective link
decomposition and its conservation invariant (routed link bytes ==
static post-opt wire bytes, for every collective kind on a sweep of
mesh shapes), the wire_bytes audit pins for CommFused shared slots and
chunked collectives, sampled per-collective timing on the 2x2 CPU host
mesh, skew-episode edge triggering + the flight dump naming the slow
core, the ``/mesh`` scrape and strict Prometheus exposition grammar,
``analyzer mesh`` text + ``--json``, and the off-switch contract (an
unscoped dispatch path never even builds the scope).
"""

import json
import re
import types
import urllib.error
import urllib.request

import numpy as np
import pytest

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T
import tilelang_mesh_tpu.observability as obs
from tilelang_mesh_tpu.observability import flight
from tilelang_mesh_tpu.observability import meshscope as ms
from tilelang_mesh_tpu.observability.meshscope import (
    MESH_SCHEMA, MeshScope, core_name, link_name, route_record)
from tilelang_mesh_tpu.parallel import mesh_config
from tilelang_mesh_tpu.parallel.lowering import (
    _schedule_hops, _schedule_steps)
from tilelang_mesh_tpu.transform import pass_config

MESH = (2, 2)
NROW, NCOL = MESH
SHAPE = (8, 32)
TARGET = f"cpu-mesh[{NROW}x{NCOL}]"

_DIR_CODE = {"h": 0, "v": 1, "all": 2}


@pytest.fixture(autouse=True)
def _fresh_state(monkeypatch):
    """Scope state is process-global (singleton, histograms, tracer):
    every test starts clean and leaves no armed knobs behind."""
    for var in ("TL_TPU_MESH_SCOPE", "TL_TPU_RUNTIME_SAMPLE",
                "TL_TPU_MESH_SKEW", "TL_TPU_MESH_SKEW_ALPHA",
                "TL_TPU_MESH_SKEW_MADS", "TL_TPU_MESH_SKEW_MIN_REL",
                "TL_TPU_MESH_SKEW_WARMUP", "TL_TPU_MESH_SKEW_SUSTAIN"):
        monkeypatch.delenv(var, raising=False)
    tilelang.clear_cache()
    obs.reset()
    yield
    tilelang.clear_cache()
    obs.reset()


def _need_mesh():
    import jax
    if len(jax.devices()) < NROW * NCOL:
        pytest.skip(f"needs {NROW * NCOL} devices")


# ---------------------------------------------------------------------------
# helpers: static records + stub kernels (no device needed)
# ---------------------------------------------------------------------------


def _hops_for(op, mesh, dirname, src_core=0, dst_core=0):
    """Schedule hop count straight from the lowering's own schedules —
    the ground truth the route model must conserve against."""
    nrow, ncol = mesh
    kind = op[len("fused_"):] if op.startswith("fused_") else op
    if kind == "put":
        sr, sc = divmod(src_core, ncol)
        dr, dc = divmod(dst_core, ncol)
        return abs(sr - dr) + abs(sc - dc)
    d = _DIR_CODE[dirname]
    if kind == "broadcast":
        steps = _schedule_steps("broadcast", nrow, ncol, d,
                                divmod(src_core, ncol))
    elif kind == "allgather":
        steps = _schedule_steps("all_gather", nrow, ncol, d)
    else:
        steps = _schedule_steps("all_reduce", nrow, ncol, d)
    return _schedule_hops(steps, nrow, ncol)


def _static_rec(op, mesh, dirname="all", payload=4096, segment=1, **kw):
    """A JSON-safe attrs["collectives"] record shaped exactly like
    parallel/lowering._account_collective emits."""
    hops = _hops_for(op, mesh, dirname,
                     src_core=kw.get("src_core", 0),
                     dst_core=kw.get("dst_core", 0))
    return {"kernel": "stub", "segment": segment, "op": op,
            "dir": dirname,
            "axis": {"h": "y", "v": "x", "all": "x,y"}[dirname],
            "payload_bytes": payload, "hops": hops,
            "wire_bytes": payload * hops, **kw}


def _stub_kernel(recs, mesh=MESH, name="stub"):
    """The artifact surface note_dispatch consumes — enough to drive
    the ledger without compiling or dispatching anything."""
    art = types.SimpleNamespace(name=name, mesh_config=mesh,
                                attrs={"collectives": recs})
    return types.SimpleNamespace(artifact=art)


def _ksum_program():
    """The smoke kernel: per-row local reduce + all-direction
    all_reduce on the 2x2 host mesh."""
    with mesh_config(*MESH):
        @T.prim_func
        def ksum(A: T.MeshTensor((NROW * NCOL * SHAPE[0], SHAPE[1]),
                                 T.MeshShardingPolicy(cross_mesh_dim=0),
                                 MESH, "float32"),
                 B: T.MeshTensor((NROW * NCOL * SHAPE[0], 1),
                                 T.MeshShardingPolicy(cross_mesh_dim=0),
                                 MESH, "float32")):
            with T.Kernel(1) as bx:
                x = T.alloc_fragment(SHAPE, "float32")
                o = T.alloc_fragment((SHAPE[0], 1), "float32")
                T.copy(A, x)
                T.comm.all_reduce(x, o, "sum", "all", dim=1)
                T.copy(o, B)
        return ksum


MESHES = [(1, 2), (2, 2), (2, 4), (4, 2), (3, 3), (4, 4), (1, 8)]


# ---------------------------------------------------------------------------
# route model
# ---------------------------------------------------------------------------


class TestRouteModel:
    def test_core_and_link_names(self):
        assert core_name(0, 4) == "x0y0"
        assert core_name(5, 4) == "x1y1"
        assert core_name(7, 2) == "x3y1"
        assert link_name((0, 1), 2) == "x0y0->x0y1"
        assert link_name((3, 1), 2) == "x1y1->x0y1"

    def test_links_are_mesh_neighbors(self):
        """Every routed link is one directed ICI hop between adjacent
        cores — the route model can never invent a diagonal wire."""
        for mesh in MESHES:
            nrow, ncol = mesh
            for dirname in ("h", "v", "all"):
                for op in ("allreduce", "allgather"):
                    rec = _static_rec(op, mesh, dirname)
                    for (a, b) in route_record(rec, nrow, ncol):
                        ra, ca = divmod(a, ncol)
                        rb, cb = divmod(b, ncol)
                        assert abs(ra - rb) + abs(ca - cb) == 1
                        if dirname == "h":
                            assert ra == rb
                        if dirname == "v":
                            assert ca == cb

    def test_conservation_allreduce_allgather(self):
        """The invariant per record: routed link-byte totals equal
        payload x schedule hops == wire_bytes, on every mesh shape and
        direction."""
        for mesh in MESHES:
            nrow, ncol = mesh
            for dirname in ("h", "v", "all"):
                for op in ("allreduce", "allgather"):
                    rec = _static_rec(op, mesh, dirname, payload=4096)
                    routed = route_record(rec, nrow, ncol)
                    assert sum(routed.values()) == rec["wire_bytes"], \
                        f"{op} {dirname} on {mesh}"

    def test_conservation_broadcast_every_src(self):
        for mesh in MESHES:
            nrow, ncol = mesh
            for dirname in ("h", "v", "all"):
                for src in range(nrow * ncol):
                    rec = _static_rec("broadcast", mesh, dirname,
                                      payload=512, src_core=src)
                    routed = route_record(rec, nrow, ncol)
                    assert sum(routed.values()) == rec["wire_bytes"], \
                        f"broadcast src={src} {dirname} on {mesh}"

    def test_put_walks_manhattan_path(self):
        mesh = (3, 3)
        nrow, ncol = mesh
        for src in range(9):
            for dst in range(9):
                rec = _static_rec("put", mesh, payload=256,
                                  src_core=src, dst_core=dst)
                routed = route_record(rec, nrow, ncol)
                assert sum(routed.values()) == rec["wire_bytes"]
                sr, sc = divmod(src, ncol)
                dr, dc = divmod(dst, ncol)
                hops = abs(sr - dr) + abs(sc - dc)
                # one distinct link per hop, payload each
                assert len(routed) == hops
                if src == dst:
                    assert routed == {}

    def test_fused_routes_as_inner_kind(self):
        """A fused record routes like its inner collective with the
        (distinct-slot summed) fused payload."""
        for mesh in ((2, 2), (2, 4)):
            nrow, ncol = mesh
            fused = _static_rec("fused_allreduce", mesh, "h",
                                payload=8192, members=2, slots=2)
            plain = _static_rec("allreduce", mesh, "h", payload=8192)
            assert route_record(fused, nrow, ncol) == \
                route_record(plain, nrow, ncol)
            assert sum(route_record(fused, nrow, ncol).values()) == \
                fused["wire_bytes"]

    def test_zero_payload_routes_nothing(self):
        assert route_record({"op": "allreduce", "dir": "all",
                             "payload_bytes": 0}, 2, 2) == {}


# ---------------------------------------------------------------------------
# satellite 2: wire_bytes audit pins (CommFused shared slots + chunking)
# ---------------------------------------------------------------------------


def _lower(pf, **cfg):
    if cfg:
        with pass_config(cfg):
            return tilelang.lower(pf, target=TARGET)
    return tilelang.lower(pf, target=TARGET)


def _mesh_global(shape):
    return T.MeshTensor(shape, T.MeshShardingPolicy(cross_mesh_dim=0),
                        MESH, "float32")


class TestWireBytesAudit:
    """Pin the static accounting the ledger conserves against: fused
    records carry hops x distinct-slot payload sum (a shared wire slot
    is counted once), chunked records carry the unchunked wire volume
    (chunking pipelines bytes, it does not add or remove them)."""

    def test_fused_distinct_slots_sum(self):
        """Two distinct-payload all_reduces fuse into one record whose
        payload is the SUM of both slots."""
        with mesh_config(*MESH):
            @T.prim_func
            def k(A: _mesh_global((NROW * NCOL * SHAPE[0], SHAPE[1])),
                  B: _mesh_global((NROW * NCOL * SHAPE[0], 1)),
                  C: _mesh_global((NROW * NCOL * SHAPE[0], 1))):
                with T.Kernel(1) as bx:
                    x = T.alloc_fragment(SHAPE, "float32")
                    y = T.alloc_fragment(SHAPE, "float32")
                    o1 = T.alloc_fragment((SHAPE[0], 1), "float32")
                    o2 = T.alloc_fragment((SHAPE[0], 1), "float32")
                    T.copy(A, x)
                    T.copy(A, y)
                    T.comm.all_reduce(x, o1, "sum", "h", dim=1)
                    T.comm.all_reduce(y, o2, "sum", "h", dim=1)
                    T.copy(o1, B)
                    T.copy(o2, C)
        recs = _lower(k).attrs["collectives"]
        fused = [r for r in recs if r["op"] == "fused_allreduce"]
        assert len(fused) == 1
        rec = fused[0]
        assert rec["members"] == 2 and rec["slots"] == 2
        # each all_reduce slot wires its out-sized chunk: (SHAPE[0], 1)
        # float32 per member, both distinct
        slot = SHAPE[0] * 4
        assert rec["payload_bytes"] == 2 * slot
        assert rec["wire_bytes"] == rec["hops"] * 2 * slot
        # and the route model conserves the fused record exactly
        routed = route_record(rec, NROW, NCOL)
        assert sum(routed.values()) == rec["wire_bytes"]

    def test_fused_shared_slot_counted_once(self):
        """A duplicate broadcast is dropped and a same-payload broadcast
        to a second destination SHARES the wire slot: one slot's bytes
        on the wire, pre-opt accounting remembers all three."""
        with mesh_config(*MESH):
            @T.prim_func
            def k(A: _mesh_global((NROW * NCOL * SHAPE[0], SHAPE[1])),
                  B: _mesh_global((NROW * NCOL * SHAPE[0], SHAPE[1])),
                  C: _mesh_global((NROW * NCOL * SHAPE[0], SHAPE[1]))):
                with T.Kernel(1) as bx:
                    x = T.alloc_shared(SHAPE, "float32")
                    d1 = T.alloc_shared(SHAPE, "float32")
                    d2 = T.alloc_shared(SHAPE, "float32")
                    T.copy(A, x)
                    T.comm.broadcast(x, d1, (0, 1), "h")
                    T.comm.broadcast(x, d1, (0, 1), "h")
                    T.comm.broadcast(x, d2, (0, 1), "h")
                    T.copy(d1, B)
                    T.copy(d2, C)
        recs = _lower(k).attrs["collectives"]
        fused = [r for r in recs if r["op"] == "fused_broadcast"]
        assert len(fused) == 1
        rec = fused[0]
        assert rec["members"] == 2 and rec["slots"] == 1
        one_slot = SHAPE[0] * SHAPE[1] * 4
        assert rec["payload_bytes"] == one_slot
        assert rec["wire_bytes"] == rec["hops"] * one_slot
        # 2 surviving members + 1 dropped duplicate, unfused
        assert rec["pre_opt_wire_bytes"] == 3 * rec["wire_bytes"]
        routed = route_record(rec, NROW, NCOL)
        assert sum(routed.values()) == rec["wire_bytes"]

    def test_chunked_wire_bytes_unchanged(self):
        """Chunking splits the transfer for overlap; the wire volume —
        and therefore the ledger — is identical to the unchunked op."""
        def prog():
            with mesh_config(*MESH):
                @T.prim_func
                def k(A: _mesh_global((NROW * NCOL * SHAPE[0],
                                       SHAPE[1])),
                      B: _mesh_global((NROW * NCOL, NCOL, SHAPE[0],
                                       SHAPE[1]))):
                    with T.Kernel(1) as bx:
                        send = T.alloc_shared(SHAPE, "float32")
                        recv = T.alloc_shared((NCOL, *SHAPE), "float32")
                        T.copy(A, send)
                        T.comm.all_gather(send, recv, "h")
                        T.copy(recv, B[0, 0, 0])
                return k

        plain = [r for r in _lower(prog()).attrs["collectives"]
                 if r["op"] == "allgather"]
        chunked = [r for r in
                   _lower(prog(), **{"tl.tpu.comm_chunk_bytes": 1024})
                   .attrs["collectives"]
                   if r["op"] == "allgather" and r.get("chunks")]
        assert len(plain) == 1 and len(chunked) == 1
        assert chunked[0]["chunks"] > 1
        assert chunked[0]["payload_bytes"] == plain[0]["payload_bytes"]
        assert chunked[0]["wire_bytes"] == plain[0]["wire_bytes"]
        assert chunked[0]["pre_opt_wire_bytes"] == plain[0]["wire_bytes"]
        routed = route_record(chunked[0], NROW, NCOL)
        assert sum(routed.values()) == chunked[0]["wire_bytes"]


# ---------------------------------------------------------------------------
# ledger + conservation (stub kernels: no device)
# ---------------------------------------------------------------------------


class TestLedger:
    def test_note_dispatch_conserves(self):
        scope = MeshScope()
        rec = _static_rec("allreduce", MESH, "all", payload=1024)
        kern = _stub_kernel([rec])
        for _ in range(5):
            scope.note_dispatch(kern)
        cons = scope.conservation()
        assert cons["ok"] is True
        assert cons["ledger_bytes"] == 5 * rec["wire_bytes"] > 0
        assert cons["kernels"]["stub"]["dispatches"] == 5
        assert cons["kernels"]["stub"]["wire_bytes_per_dispatch"] == \
            rec["wire_bytes"]

    def test_multi_kernel_shared_pool(self):
        scope = MeshScope()
        a = _stub_kernel([_static_rec("allreduce", MESH, "h",
                                      payload=512)], name="a")
        b = _stub_kernel([_static_rec("broadcast", MESH, "all",
                                      payload=256, src_core=0)],
                         name="b")
        scope.note_dispatch(a)
        scope.note_dispatch(a)
        scope.note_dispatch(b)
        cons = scope.conservation()
        assert cons["ok"] is True
        assert set(cons["kernels"]) == {"a", "b"}
        assert cons["ledger_bytes"] == cons["expected_bytes"]

    def test_summary_links_and_top(self, monkeypatch):
        monkeypatch.setenv("TL_TPU_MESH_SCOPE", "1")
        scope = MeshScope()
        rec = _static_rec("allreduce", MESH, "all", payload=2048)
        scope.note_dispatch(_stub_kernel([rec]))
        s = scope.summary()
        assert s["enabled"] is True
        assert s["mesh"] == [NROW, NCOL]
        # an all-direction all_reduce on 2x2 touches every directed link
        assert len(s["links"]) == 8
        assert all(re.fullmatch(r"x\d+y\d+->x\d+y\d+", n)
                   for n in s["links"])
        assert all(row["bytes"] > 0 for row in s["links"].values())
        assert s["top_links"] and len(s["top_links"]) <= 8
        assert s["ici_link_bytes_per_s"] > 0
        assert s["conservation"]["ok"] is True

    def test_mismatched_record_drops_table(self):
        """A record whose wire_bytes the route model cannot reproduce
        must NOT silently ledger wrong bytes: the whole kernel's table
        is dropped, the conservation gate simply has no entry."""
        scope = MeshScope()
        bad = _static_rec("allreduce", MESH, "all", payload=1024)
        bad["wire_bytes"] += 1   # corrupt the static side
        scope.note_dispatch(_stub_kernel([bad], name="bad"))
        cons = scope.conservation()
        assert cons["ledger_bytes"] == 0
        assert "bad" not in cons["kernels"]


# ---------------------------------------------------------------------------
# sampled-timing smoke on the 2x2 CPU host mesh (real dispatch path)
# ---------------------------------------------------------------------------


class TestDispatchSmoke:
    def test_scoped_dispatch_end_to_end(self, monkeypatch):
        """The real hook: MeshKernel.__call__ ledgers every scoped
        dispatch, samples collective timings into comm.latency, and the
        numerics are untouched by scoping."""
        _need_mesh()
        monkeypatch.setenv("TL_TPU_MESH_SCOPE", "1")
        monkeypatch.setenv("TL_TPU_RUNTIME_SAMPLE", "1")
        monkeypatch.setattr(ms, "_scope", None)
        kern = tilelang.compile(_ksum_program(), target=TARGET)
        a = np.ones((NROW * NCOL * SHAPE[0], SHAPE[1]), np.float32)
        outs = [np.asarray(kern(a)) for _ in range(3)]
        # numerics: local row-sum then psum over the 4 cores
        expect = np.full((NROW * NCOL * SHAPE[0], 1),
                         NROW * NCOL * SHAPE[1], np.float32)
        for o in outs:
            np.testing.assert_allclose(o, expect, rtol=1e-5)
        scope = ms.get_scope()
        cons = scope.conservation()
        name = kern.artifact.name
        assert cons["ok"] is True and cons["ledger_bytes"] > 0
        assert cons["kernels"][name]["dispatches"] == 3
        s = scope.summary()
        assert len(s["links"]) == 8
        rows = [r for r in s["collectives"] if r["kernel"] == name]
        assert rows and rows[0]["samples"] >= 1
        assert rows[0]["measured_ewma_ms"] > 0
        assert rows[0]["measured_min_ms"] <= rows[0]["measured_ewma_ms"] \
            or rows[0]["samples"] == 1
        assert any(k.startswith("allreduce@") for k in s["latency"])

    def test_off_switch_builds_nothing(self, monkeypatch):
        """Off is OFF: with TL_TPU_MESH_SCOPE unset a dispatch crosses
        the hook's single env read and the scope singleton is never
        even constructed."""
        _need_mesh()
        monkeypatch.setattr(ms, "_scope", None)
        assert ms.mesh_scope_enabled() is False
        kern = tilelang.compile(_ksum_program(), target=TARGET)
        a = np.ones((NROW * NCOL * SHAPE[0], SHAPE[1]), np.float32)
        kern(a)
        kern(a)
        assert ms._scope is None


# ---------------------------------------------------------------------------
# skew detection
# ---------------------------------------------------------------------------

SWEEP_SLOW = {"x0y0": 1e-3, "x0y1": 1e-3, "x1y0": 1e-3, "x1y1": 3e-3}
SWEEP_FLAT = {k: 1e-3 for k in SWEEP_SLOW}


def _skew_knobs(monkeypatch, warmup=4, sustain=2, alpha="1.0"):
    """alpha=1.0 makes the EWMA track the last ratio exactly — the
    edge-trigger tests become deterministic step responses."""
    monkeypatch.setenv("TL_TPU_MESH_SKEW", "1")
    monkeypatch.setenv("TL_TPU_MESH_SKEW_ALPHA", alpha)
    monkeypatch.setenv("TL_TPU_MESH_SKEW_WARMUP", str(warmup))
    monkeypatch.setenv("TL_TPU_MESH_SKEW_SUSTAIN", str(sustain))


class TestSkew:
    def test_episode_fires_exactly_once(self, monkeypatch):
        _skew_knobs(monkeypatch)
        scope = MeshScope()
        fired = []
        for _ in range(40):
            fired += scope.observe_shards(dict(SWEEP_SLOW), probe="t")
        assert len(fired) == 1, "sustained skew must fire exactly once"
        ev = fired[0]
        assert ev["shard"] == "x1y1"
        assert ev["ratio"] > ev["threshold"] > 1.0
        assert ev["episode"] == 1 and ev["probe"] == "t"
        skew = scope.summary()["skew"]
        assert skew["episodes"] == 1 and skew["sweeps"] == 40
        active = {a["shard"]: a for a in skew["active"]}
        assert active["x1y1"]["episodes"] == 1

    def test_slow_core_links_named(self, monkeypatch):
        """The event names the straggler's ICI links, both directions
        to each mesh neighbor (x1y1 on 2x2 has two neighbors)."""
        _skew_knobs(monkeypatch)
        scope = MeshScope()
        scope.note_dispatch(_stub_kernel(
            [_static_rec("allreduce", MESH, "all", payload=64)]))
        fired = []
        for _ in range(40):
            fired += scope.observe_shards(dict(SWEEP_SLOW))
        assert set(fired[0]["links"]) == {
            "x1y1->x0y1", "x0y1->x1y1", "x1y1->x1y0", "x1y0->x1y1"}

    def test_recovery_rearms_edge(self, monkeypatch):
        _skew_knobs(monkeypatch)
        scope = MeshScope()
        fired = []
        for _ in range(20):
            fired += scope.observe_shards(dict(SWEEP_SLOW))
        assert len(fired) == 1
        for _ in range(20):   # recovery clears the episode latch
            fired += scope.observe_shards(dict(SWEEP_FLAT))
        assert len(fired) == 1
        for _ in range(20):   # a second sustained episode refires
            fired += scope.observe_shards(dict(SWEEP_SLOW))
        assert len(fired) == 2
        assert scope.summary()["skew"]["episodes"] == 2

    def test_warmup_gates_firing(self, monkeypatch):
        _skew_knobs(monkeypatch, warmup=10, sustain=2)
        scope = MeshScope()
        fired = []
        for _ in range(8):    # under warmup: never fires
            fired += scope.observe_shards(dict(SWEEP_SLOW))
        assert fired == []

    def test_disabled_feed_is_inert(self, monkeypatch):
        monkeypatch.setenv("TL_TPU_MESH_SKEW", "0")
        scope = MeshScope()
        for _ in range(40):
            assert scope.observe_shards(dict(SWEEP_SLOW)) == []
        assert scope.summary()["skew"]["sweeps"] == 0

    def test_flight_dump_names_core(self, monkeypatch, tmp_path):
        _skew_knobs(monkeypatch)
        flight.configure(dump_dir=tmp_path)
        try:
            scope = MeshScope()
            for _ in range(40):
                scope.observe_shards(dict(SWEEP_SLOW), probe="t")
        finally:
            flight.configure(None)
        dumps = []
        for p in sorted(tmp_path.glob("flight_*.jsonl")):
            with open(p, encoding="utf-8") as fh:
                head = json.loads(fh.readline())
            if head.get("reason") == "mesh_skew":
                dumps.append(head)
        assert len(dumps) == 1
        attrs = dumps[0]["attrs"]
        assert attrs["shard"] == "x1y1"
        assert attrs["links"] and attrs["episode"] == 1


# ---------------------------------------------------------------------------
# surfaces: /mesh, Prometheus grammar, metrics_summary, analyzer mesh
# ---------------------------------------------------------------------------


def _populate_module_scope(monkeypatch, samples=False):
    """Route ledger traffic through the MODULE singleton (what the
    exporters read), via stub dispatches."""
    monkeypatch.setenv("TL_TPU_MESH_SCOPE", "1")
    monkeypatch.setattr(ms, "_scope", None)
    kern = _stub_kernel([_static_rec("allreduce", MESH, "all",
                                     payload=2048)], name="probe")
    for _ in range(4):
        ms.get_scope().note_dispatch(kern)
    if samples:
        ms.get_scope().sample_dispatch(kern)
    return kern


class TestSurfaces:
    def test_mesh_endpoint(self, monkeypatch):
        from tilelang_mesh_tpu.observability import server
        _populate_module_scope(monkeypatch)
        srv = server.start_server(port=0)
        try:
            with urllib.request.urlopen(f"{srv.url}/mesh",
                                        timeout=5) as r:
                assert r.status == 200
                snap = json.loads(r.read().decode())
            # unknown paths 404 with the endpoint index as the body
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(f"{srv.url}/nope", timeout=5)
            index = json.loads(ei.value.read().decode())
        finally:
            srv.stop()
        assert snap["schema"] == MESH_SCHEMA
        assert snap["dispatches"] == {"probe": 4}
        assert snap["conservation"]["ok"] is True
        assert len(snap["links"]) == 8
        assert "/mesh" in index["endpoints"]

    def test_prometheus_grammar_strict(self, monkeypatch):
        """Every emitted mesh line must parse under the exposition
        grammar: TYPE headers, one gauge sample per link label."""
        from tilelang_mesh_tpu.observability.export import \
            to_prometheus_text
        _populate_module_scope(monkeypatch)
        text = to_prometheus_text()
        mesh_lines = [ln for ln in text.splitlines()
                      if "tl_tpu_mesh" in ln]
        assert "# TYPE tl_tpu_mesh_link_bytes gauge" in mesh_lines
        sample_re = re.compile(
            r'^tl_tpu_mesh_link_(bytes|util)'
            r'\{link="x\d+y\d+->x\d+y\d+"\} '
            r'-?\d+(\.\d+)?([eE][+-]?\d+)?$')
        samples = [ln for ln in mesh_lines if not ln.startswith("#")]
        assert len(samples) >= 8
        for ln in samples:
            assert sample_re.fullmatch(ln), f"bad exposition line: {ln}"
        byte_lines = [ln for ln in samples
                      if ln.startswith("tl_tpu_mesh_link_bytes")]
        assert len(byte_lines) == 8

    def test_prometheus_absent_when_unscoped(self, monkeypatch):
        from tilelang_mesh_tpu.observability.export import \
            to_prometheus_text
        monkeypatch.setattr(ms, "_scope", None)
        assert "tl_tpu_mesh" not in to_prometheus_text()

    def test_metrics_summary_mesh_section(self, monkeypatch):
        from tilelang_mesh_tpu.observability import metrics_summary
        _populate_module_scope(monkeypatch)
        mesh = metrics_summary()["mesh"]
        assert mesh["enabled"] is True
        assert mesh["dispatches"] == {"probe": 4}
        assert mesh["conservation"]["ok"] is True

    def test_metrics_summary_disabled_stub(self, monkeypatch):
        from tilelang_mesh_tpu.observability import metrics_summary
        monkeypatch.setattr(ms, "_scope", None)
        mesh = metrics_summary()["mesh"]
        assert mesh["mesh"] is None and mesh["dispatches"] == {}

    def test_analyzer_mesh_text_and_json(self, monkeypatch, tmp_path,
                                         capsys):
        from tilelang_mesh_tpu.tools import analyzer
        _populate_module_scope(monkeypatch)
        snap = ms.mesh_snapshot()
        p = tmp_path / "mesh.json"
        p.write_text(json.dumps(snap))
        assert analyzer.main(["mesh", str(p)]) == 0
        out = capsys.readouterr().out
        # the heatmap names cores, the table names links
        assert "x0y0" in out and "x0y0->x0y1" in out
        assert "conservation" in out.lower()
        assert analyzer.main(["mesh", str(p), "--json"]) == 0
        parsed = json.loads(capsys.readouterr().out)
        assert parsed["schema"] == MESH_SCHEMA
        assert parsed["dispatches"] == {"probe": 4}

    def test_analyzer_mesh_rejects_garbage(self, tmp_path, capsys):
        from tilelang_mesh_tpu.tools import analyzer
        p = tmp_path / "nope.json"
        p.write_text(json.dumps({"hello": "world"}))
        assert analyzer.main(["mesh", str(p)]) == 1
        capsys.readouterr()

    def test_jsonl_mesh_line(self, monkeypatch):
        from tilelang_mesh_tpu.observability.export import to_jsonl
        _populate_module_scope(monkeypatch)
        lines = [json.loads(ln) for ln in to_jsonl().splitlines()]
        mesh = [ln for ln in lines if ln.get("type") == "mesh"]
        assert len(mesh) == 1
        assert mesh[0]["schema"] == MESH_SCHEMA
        assert mesh[0]["dispatches"] == {"probe": 4}


# ---------------------------------------------------------------------------
# fault-site attribution (sampled path visits comm.collective)
# ---------------------------------------------------------------------------


class TestFaultAttribution:
    def test_injected_fault_lands_on_collective(self, monkeypatch):
        from tilelang_mesh_tpu.resilience import inject
        kern = _populate_module_scope(monkeypatch)
        with inject("comm.collective", p=1.0, kind="transient",
                    times=1):
            ms.get_scope().sample_dispatch(kern)
        s = ms.get_scope().summary()
        assert s["faults"]["injected"] == 1
        hit = [r for r in s["collectives"] if r["faults"]]
        assert len(hit) == 1 and hit[0]["op"] == "allreduce"
        assert hit[0].get("last_fault")

    def test_no_fault_without_injection(self, monkeypatch):
        kern = _populate_module_scope(monkeypatch)
        ms.get_scope().sample_dispatch(kern)
        assert ms.get_scope().summary()["faults"]["injected"] == 0
