"""Regression tests for the round-2 advisor findings (ADVICE.md):

1. autotuner run_with_timeout must return promptly when a config wedges
   (previously blocked in ThreadPoolExecutor.__exit__ until the hung fn
   finished).
2. prefetch-guard redirection must never apply to inout params
   (previously corrupted untouched blocks of an aliased tensor).
3. SSA promotion must be disqualified for buffers indexed through a
   BufferLoad (e.g. an SMEM scalar) — previously a trace-time TypeError.
4. pad1 column layout must be dropped for both endpoints of split-phase
   DMA (previously mismatched .at[] window shapes between two VMEM
   scratches).
"""

import time

import numpy as np
import pytest

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T


def test_run_with_timeout_abandons_hung_config():
    import concurrent.futures

    from tilelang_mesh_tpu.autotuner import run_with_timeout

    t0 = time.perf_counter()
    with pytest.raises(concurrent.futures.TimeoutError):
        run_with_timeout(time.sleep, 0.3, 3.0)
    elapsed = time.perf_counter() - t0
    # the old context-manager version blocked ~3.0s here
    assert elapsed < 1.5, f"timeout did not abandon the worker ({elapsed:.2f}s)"


def test_run_with_timeout_propagates_errors_and_results():
    from tilelang_mesh_tpu.autotuner import run_with_timeout

    assert run_with_timeout(lambda x: x + 1, 5.0, 41) == 42
    with pytest.raises(ValueError, match="boom"):
        run_with_timeout(
            lambda: (_ for _ in ()).throw(ValueError("boom")), 5.0)


def test_prefetch_guard_not_applied_to_inout_param():
    """An inout tensor read only on pipeline step 0 must keep its other
    blocks intact: guard redirection on the input spec would write
    block-0 data over them via the unguarded output spec."""
    NB, BM, BN = 4, 8, 128

    @T.prim_func
    def bump_first(X: T.Tensor((NB * BM, BN), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_shared((BM, BN), "float32")
            for ko in T.Pipelined(NB):
                with T.If(ko == 0):
                    T.copy(X[ko * BM, 0], s)
                    for i, j in T.Parallel(BM, BN):
                        s[i, j] = s[i, j] + 1.0
                    T.copy(s, X[ko * BM, 0])

    k = tilelang.compile(bump_first)
    x = np.arange(NB * BM * BN, dtype=np.float32).reshape(NB * BM, BN)
    orig = x.copy()
    k(x)
    np.testing.assert_allclose(x[:BM], orig[:BM] + 1.0)
    np.testing.assert_allclose(x[BM:], orig[BM:])


def test_ssa_promotion_rejects_buffer_load_index():
    """A fragment read at a row index loaded from an SMEM scalar must not
    be promoted to a Python local (plain slices can't take traced
    starts); it must stay in VMEM scratch and still produce the right
    answer."""
    R, C = 8, 128

    @T.prim_func
    def pick_row(A: T.Tensor((R, C), "float32"),
                 O: T.Tensor((1, C), "float32")):
        with T.Kernel(1) as bx:
            f = T.alloc_fragment((R, C), "float32")
            iv = T.alloc_var("int32")
            for i, j in T.Parallel(R, C):
                f[i, j] = A[i, j] * 2.0
            iv[0] = 3
            T.copy(f[iv[0], 0], O)

    k = tilelang.compile(pick_row)
    a = np.random.default_rng(0).standard_normal((R, C)).astype(np.float32)
    out = np.empty((1, C), np.float32)
    k(a, out)
    np.testing.assert_allclose(out[0], a[3] * 2.0, rtol=1e-6)


def test_pad1_dropped_for_async_copy_between_scratches():
    """Split-phase DMA between two VMEM scratches where one endpoint
    would otherwise be (N,1)-padded: rt.dma windows both sides with
    .at[] and applies no pad column, so the shapes must agree."""
    N = 128

    @T.prim_func
    def relay(A: T.Tensor((N,), "float32"), O: T.Tensor((N,), "float32")):
        with T.Kernel(1) as bx:
            s1 = T.alloc_shared((N,), "float32")
            s2 = T.alloc_shared((N,), "float32")
            sems = T.alloc_semaphore(2)
            T.copy_async(A, s1, sems, 0)
            T.copy_wait(A, s1, sems, 0)
            T.copy_async(s1, s2, sems, 1)
            T.copy_wait(s1, s2, sems, 1)
            T.copy(s2, O)

    k = tilelang.compile(relay)
    a = np.random.default_rng(1).standard_normal((N,)).astype(np.float32)
    out = np.empty_like(a)
    k(a, out)
    np.testing.assert_allclose(out, a, rtol=1e-6)
