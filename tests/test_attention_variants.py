"""GQA + chunked linear attention."""

import numpy as np
import pytest

import jax.numpy as jnp

from tilelang_mesh_tpu.ops.gqa import gqa_attention
from tilelang_mesh_tpu.ops.linear_attention import (
    linear_attention, linear_attention_reference)
from tilelang_mesh_tpu.utils.tensor import assert_allclose


def _gqa_reference(q, k, v, causal, sm_scale):
    from tilelang_mesh_tpu.ops.flash_attention import _reference_attention
    B, Hq, Sq, D = q.shape
    Hkv = k.shape[1]
    g = Hq // Hkv
    k = jnp.repeat(k, g, axis=1)
    v = jnp.repeat(v, g, axis=1)
    return _reference_attention(q, k, v, causal, sm_scale)


@pytest.mark.parametrize("causal", [False, True])
def test_gqa(causal):
    B, Hq, Hkv, S, D = 1, 8, 2, 256, 64
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, Hq, S, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.float32)
    out = gqa_attention(q, k, v, causal=causal)
    ref = _gqa_reference(q, k, v, causal, 1.0 / np.sqrt(D))
    assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2, atol=2e-2)


def test_gqa_kv_blockspec_has_divided_map():
    """The KV fetch must ride a BlockSpec with a `// group` index map, not
    the DMA fallback."""
    from tilelang_mesh_tpu.ops.gqa import gqa_fwd_kernel
    k = gqa_fwd_kernel(1, 8, 2, 256, 256, 64, 128, 128, False, 0.125,
                       "float32")
    assert "// 4" in k.get_kernel_source()
    assert "K: block" in k.get_plan()


def test_linear_attention():
    B, H, S, DK, DV = 1, 2, 512, 64, 64
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.standard_normal((B, H, S, DK)) * 0.2, jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, S, DK)) * 0.2, jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, S, DV)) * 0.2, jnp.float32)
    out = linear_attention(q, k, v, chunk=128)
    ref = linear_attention_reference(q, k, v)
    assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2, atol=2e-1)
