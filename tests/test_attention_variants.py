"""GQA + chunked linear attention."""

import numpy as np
import pytest

import jax.numpy as jnp

from tilelang_mesh_tpu.ops.gqa import gqa_attention
from tilelang_mesh_tpu.ops.linear_attention import (
    linear_attention, linear_attention_reference)
from tilelang_mesh_tpu.utils.tensor import assert_allclose


def _gqa_reference(q, k, v, causal, sm_scale):
    from tilelang_mesh_tpu.ops.flash_attention import _reference_attention
    B, Hq, Sq, D = q.shape
    Hkv = k.shape[1]
    g = Hq // Hkv
    k = jnp.repeat(k, g, axis=1)
    v = jnp.repeat(v, g, axis=1)
    return _reference_attention(q, k, v, causal, sm_scale)


@pytest.mark.parametrize("causal", [False, True])
def test_gqa(causal):
    B, Hq, Hkv, S, D = 1, 8, 2, 256, 64
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, Hq, S, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.float32)
    out = gqa_attention(q, k, v, causal=causal)
    ref = _gqa_reference(q, k, v, causal, 1.0 / np.sqrt(D))
    assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2, atol=2e-2)


def test_gqa_kv_blockspec_has_divided_map():
    """The KV fetch must ride a BlockSpec with a `// group` index map, not
    the DMA fallback."""
    from tilelang_mesh_tpu.ops.gqa import gqa_fwd_kernel
    k = gqa_fwd_kernel(1, 8, 2, 256, 256, 64, 128, 128, False, 0.125,
                       "float32")
    assert "// 4" in k.get_kernel_source()
    assert "K: block" in k.get_plan()


def test_linear_attention():
    B, H, S, DK, DV = 1, 2, 512, 64, 64
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.standard_normal((B, H, S, DK)) * 0.2, jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, S, DK)) * 0.2, jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, S, DV)) * 0.2, jnp.float32)
    out = linear_attention(q, k, v, chunk=128)
    ref = linear_attention_reference(q, k, v)
    assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2, atol=2e-1)


def test_linear_attention_bwd_matches_reference_ad():
    """dQ/dK/dV via the operand-rearranged forward kernels vs jax AD of
    the dense causal linear-attention reference."""
    import jax

    from tilelang_mesh_tpu.ops.linear_attention import (
        linear_attention, linear_attention_reference)

    B, H, S, DK, DV = 1, 2, 128, 64, 64
    rng = np.random.default_rng(41)
    q = jnp.asarray(rng.standard_normal((B, H, S, DK)) * 0.2, jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, S, DK)) * 0.2, jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, S, DV)) * 0.2, jnp.float32)
    go = jnp.asarray(rng.standard_normal((B, H, S, DV)), jnp.float32)

    def loss_kernel(q, k, v):
        return jnp.sum(linear_attention(q, k, v, chunk=64,
                                        backward="kernel") * go)

    def loss_ref(q, k, v):
        return jnp.sum(linear_attention_reference(q, k, v) * go)

    got = jax.grad(loss_kernel, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip(("dQ", "dK", "dV"), got, want):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-2, atol=3e-2, err_msg=name)


def test_linear_attention_bwd_rectangular_dims():
    """DK != DV exercises the transposed-kernel dims in the backward."""
    import jax

    from tilelang_mesh_tpu.ops.linear_attention import (
        linear_attention, linear_attention_reference)

    B, H, S, DK, DV = 1, 1, 64, 64, 128
    rng = np.random.default_rng(43)
    q = jnp.asarray(rng.standard_normal((B, H, S, DK)) * 0.2, jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, S, DK)) * 0.2, jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, S, DV)) * 0.2, jnp.float32)

    def loss_kernel(q, k, v):
        return jnp.sum(linear_attention(q, k, v, chunk=64,
                                        backward="kernel") ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(linear_attention_reference(q, k, v) ** 2)

    got = jax.grad(loss_kernel, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip(("dQ", "dK", "dV"), got, want):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-2, atol=3e-2, err_msg=name)
