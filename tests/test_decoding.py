"""Flash decoding (split-KV, paged) + MLA decode numerics
(BASELINE config #4)."""

import numpy as np
import pytest

import jax.numpy as jnp

from tilelang_mesh_tpu.ops.flash_attention import _reference_attention
from tilelang_mesh_tpu.ops.flash_decoding import (flash_decode,
                                                  flash_decode_paged)
from tilelang_mesh_tpu.ops.mla import mla_decode, mla_decode_reference
from tilelang_mesh_tpu.utils.tensor import assert_allclose


def test_flash_decode_matches_attention():
    B, H, S, D = 2, 4, 512, 64
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, H, 1, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    out = flash_decode(q, k, v, n_split=4)
    ref = _reference_attention(q, k, v, False, 1.0 / np.sqrt(D))
    assert out.shape == (B, H, 1, D)
    assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2, atol=2e-2)


def test_flash_decode_single_split():
    B, H, S, D = 1, 2, 128, 128
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.standard_normal((B, H, 1, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    out = flash_decode(q, k, v, n_split=1)
    ref = _reference_attention(q, k, v, False, 1.0 / np.sqrt(D))
    assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2, atol=2e-2)


def test_flash_decode_paged():
    B, H, D = 2, 2, 64
    page_size, pages_per_seq, n_pages = 128, 4, 16
    S = page_size * pages_per_seq
    rng = np.random.default_rng(2)
    q = jnp.asarray(rng.standard_normal((B, H, 1, D)), jnp.float32)
    kp = jnp.asarray(rng.standard_normal((n_pages, page_size, H, D)),
                     jnp.float32)
    vp = jnp.asarray(rng.standard_normal((n_pages, page_size, H, D)),
                     jnp.float32)
    table = jnp.asarray(rng.choice(n_pages, (B, pages_per_seq),
                                   replace=False), jnp.int32)
    out = flash_decode_paged(q, kp, vp, table)
    # reference: gather then dense attention
    k = jnp.take(kp, table, axis=0).reshape(B, S, H, D).transpose(0, 2, 1, 3)
    v = jnp.take(vp, table, axis=0).reshape(B, S, H, D).transpose(0, 2, 1, 3)
    ref = _reference_attention(q, k, v, False, 1.0 / np.sqrt(D))
    assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2, atol=2e-2)


def test_mla_decode():
    B, H, S, dc, dr = 2, 8, 512, 256, 32
    rng = np.random.default_rng(3)
    qc = jnp.asarray(rng.standard_normal((B, H, dc)) * 0.3, jnp.float32)
    qr = jnp.asarray(rng.standard_normal((B, H, dr)) * 0.3, jnp.float32)
    ckv = jnp.asarray(rng.standard_normal((B, S, dc)) * 0.3, jnp.float32)
    kpe = jnp.asarray(rng.standard_normal((B, S, dr)) * 0.3, jnp.float32)
    out = mla_decode(qc, qr, ckv, kpe, n_split=4)
    ref = mla_decode_reference(qc, qr, ckv, kpe)
    assert out.shape == (B, H, dc)
    assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2, atol=2e-2)


def test_paged_decode_inkernel_walk_matches_gather():
    """The in-kernel page walk over the H-major pool must equal the
    contiguous (gathered) decode bit-for-bit semantics."""
    from tilelang_mesh_tpu.ops.flash_decoding import (
        flash_decode, flash_decode_paged, flash_decode_paged_pool,
        pages_to_hmajor)

    rng = np.random.default_rng(0)
    B, H, D, PS, PP, NP = 2, 4, 64, 32, 4, 12
    q = jnp.asarray(rng.standard_normal((B, H, 1, D)), jnp.float32)
    kpages = jnp.asarray(rng.standard_normal((NP, PS, H, D)), jnp.float32)
    vpages = jnp.asarray(rng.standard_normal((NP, PS, H, D)), jnp.float32)
    table = jnp.asarray(np.stack([
        rng.choice(NP, PP, replace=False) for _ in range(B)]), jnp.int32)

    # legacy entry (page-array layout): converts + walks in-kernel
    o_walk = np.asarray(flash_decode_paged(q, kpages, vpages, table))
    # pool entry directly
    o_pool = np.asarray(flash_decode_paged_pool(
        q, pages_to_hmajor(kpages), pages_to_hmajor(vpages), table, PS))
    # ground truth: gather then contiguous decode
    k = jnp.take(kpages, table, axis=0).reshape(B, PP * PS, H, D)
    v = jnp.take(vpages, table, axis=0).reshape(B, PP * PS, H, D)
    want = np.asarray(flash_decode(q, k.transpose(0, 2, 1, 3),
                                   v.transpose(0, 2, 1, 3)))
    np.testing.assert_allclose(o_walk, want, rtol=2e-2, atol=2e-2)
    np.testing.assert_allclose(o_pool, want, rtol=2e-2, atol=2e-2)
