"""Block-sparse attention vs dense-masked reference."""

import numpy as np

import jax.numpy as jnp

from tilelang_mesh_tpu.ops.blocksparse_attention import (
    blocksparse_attention, blocksparse_reference)
from tilelang_mesh_tpu.utils.tensor import assert_allclose


def test_blocksparse_attention():
    B, H, S, D, bm, bn = 1, 2, 512, 64, 128, 128
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    mask = jnp.asarray(rng.integers(0, 2, (B, H, S // bm, S // bn)),
                       jnp.int32)
    out = blocksparse_attention(q, k, v, mask, block_M=bm, block_N=bn)
    ref = blocksparse_reference(q, k, v, mask, bm, bn)
    assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2, atol=2e-2)


def test_blocksparse_fully_masked_rows_are_zero():
    B, H, S, D, bm, bn = 1, 1, 256, 64, 128, 128
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    mask = jnp.zeros((B, H, S // bm, S // bn), jnp.int32)
    mask = mask.at[0, 0, 0, :].set(1)  # only first query block attends
    out = np.asarray(blocksparse_attention(q, k, v, mask, block_M=bm,
                                           block_N=bn))
    assert np.abs(out[0, 0, bm:]).max() == 0.0
    assert np.abs(out[0, 0, :bm]).max() > 0.0
