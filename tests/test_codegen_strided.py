"""Strided and fused-axis T.Parallel access in the vectorizer
(tilelang_mesh_tpu/codegen/exprgen.py analyze_indices)."""

import numpy as np
import pytest

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T


def test_strided_gather():
    M, N, S = 32, 128, 2

    @T.prim_func
    def strided(A: T.Tensor((M * S, N), "float32"),
                B: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            a = T.alloc_shared((M * S, N), "float32")
            b = T.alloc_shared((M, N), "float32")
            T.copy(A, a)
            for i, j in T.Parallel(M, N):
                b[i, j] = a[i * S, j]
            T.copy(b, B)

    k = tilelang.compile(strided)
    a = np.random.default_rng(0).standard_normal((M * S, N),
                                                 dtype=np.float32)
    np.testing.assert_allclose(np.asarray(k(a)), a[::S], rtol=1e-5)


def test_strided_scatter():
    M, N, S = 16, 128, 3

    @T.prim_func
    def scatter(A: T.Tensor((M, N), "float32"),
                B: T.Tensor((M * S, N), "float32")):
        with T.Kernel(1) as bx:
            a = T.alloc_shared((M, N), "float32")
            b = T.alloc_shared((M * S, N), "float32")
            T.copy(A, a)
            T.fill(b, 0)
            for i, j in T.Parallel(M, N):
                b[i * S, j] = a[i, j]
            T.copy(b, B)

    k = tilelang.compile(scatter)
    a = np.random.default_rng(1).standard_normal((M, N), dtype=np.float32)
    ref = np.zeros((M * S, N), np.float32)
    ref[::S] = a
    np.testing.assert_allclose(np.asarray(k(a)), ref, rtol=1e-5)


def test_fused_axis_transpose():
    B, M, K = 4, 8, 128

    @T.prim_func
    def fused(A: T.Tensor((B, M * K), "float32"),
              Bo: T.Tensor((B, K * M), "float32")):
        with T.Kernel(1) as bx:
            a = T.alloc_shared((B, M * K), "float32")
            b = T.alloc_shared((B, K * M), "float32")
            T.copy(A, a)
            for i, p, j in T.Parallel(B, M, K):
                b[i, j * M + p] = a[i, p * K + j] * 2.0
            T.copy(b, Bo)

    k = tilelang.compile(fused)
    a = np.random.default_rng(2).standard_normal((B, M * K),
                                                 dtype=np.float32)
    ref = a.reshape(B, M, K).transpose(0, 2, 1).reshape(B, K * M) * 2
    np.testing.assert_allclose(np.asarray(k(a)), ref, rtol=1e-5)


def test_fused_axis_requires_tight_nesting():
    @T.prim_func
    def bad(A: T.Tensor((4, 64), "float32"),
            B: T.Tensor((4, 64), "float32")):
        with T.Kernel(1) as bx:
            a = T.alloc_shared((4, 64), "float32")
            b = T.alloc_shared((4, 64), "float32")
            T.copy(A, a)
            for i, p, j in T.Parallel(4, 8, 8):
                # stride 16 != span 8 of inner var: a gap — must be rejected
                b[i, p * 16 + j] = a[i, p * 16 + j]
            T.copy(b, B)

    with pytest.raises(Exception, match="nest tightly|stride"):
        tilelang.compile(bad)


def test_bitwise_invert_and_reflected_shift():
    M, N = 8, 128

    @T.prim_func
    def bits(A: T.Tensor((M, N), "int32"),
             B: T.Tensor((M, N), "int32")):
        with T.Kernel(1) as bx:
            a = T.alloc_shared((M, N), "int32")
            b = T.alloc_shared((M, N), "int32")
            T.copy(A, a)
            for i, j in T.Parallel(M, N):
                # ~mask & v plus a reflected shift: 1 << (v & 3)
                b[i, j] = (a[i, j] & ~3) + (1 << (a[i, j] & 3))
            T.copy(b, B)

    k = tilelang.compile(bits)
    a = np.random.default_rng(0).integers(0, 1 << 20, (M, N)).astype(
        np.int32)
    ref = (a & ~3) + (1 << (a & 3))
    np.testing.assert_array_equal(np.asarray(k(a)), ref)
