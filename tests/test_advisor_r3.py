"""Regression tests for the round-3 advisor findings (ADVICE.md):

1. _copy_only_uids must exclude EVERY Region-valued CommStmt operand
   (CommAllGather send/recv, CommAllReduce buffer/out) from the
   copy-only set, so _vmem_backoff can never demote a collective
   operand to HBM behind the comm lowering's back.
2. mem2reg plan_locals must disqualify those same operands from SSA
   promotion (comm lowering needs a real ref).
3. stage_hbm must DECLINE staging for an any-param that is stored and
   then read inside one T.Parallel nest (the hoisted pre-nest read
   window would be stale) — keeping the loud codegen error instead of
   silently producing wrong results.
4. bench.py --strict exits non-zero when a config fails (CI mode).
"""

import numpy as np
import pytest

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T
from tilelang_mesh_tpu.ir import Buffer, FillStmt, Region
from tilelang_mesh_tpu.ir.stmt import CommAllGather, CommAllReduce


def _region(buf):
    shape = tuple(int(s) for s in buf.shape)
    return Region(buf, (0,) * len(shape), shape)


def _mk_param(buf, mode="any"):
    from tilelang_mesh_tpu.transform.plan import ParamPlan
    return ParamPlan(buffer=buf, role="inout", mode=mode)


def test_copy_only_excludes_all_comm_operands():
    """CommAllGather send/recv and CommAllReduce buffer/out params must
    never be classified copy-only (= demotable by _vmem_backoff)."""
    from tilelang_mesh_tpu.transform.plan import _copy_only_uids

    bufs = {n: Buffer(n, (8, 128), "float32", "global")
            for n in ("send", "recv", "acc", "out")}
    params = [_mk_param(b) for b in bufs.values()]
    stmts = [
        CommAllGather(_region(bufs["send"]), _region(bufs["recv"]),
                      direction=2, size=8 * 128),
        CommAllReduce(_region(bufs["acc"]), _region(bufs["out"]),
                      reduce_type="sum", direction=2, dim=0, clear=False),
    ]
    copy_only = _copy_only_uids(stmts, params)
    for name, b in bufs.items():
        assert b.uid not in copy_only, \
            f"comm operand {name} classified copy-only (demotable)"


def test_mem2reg_disqualifies_all_comm_operands():
    """Scratch buffers used as all_gather/all_reduce operands must stay
    memref-backed even when their def/use pattern would otherwise allow
    SSA promotion."""
    from types import SimpleNamespace

    from tilelang_mesh_tpu.transform.mem2reg import plan_locals

    s_send = Buffer("send", (8, 128), "float32", "shared")
    s_recv = Buffer("recv", (8, 128), "float32", "shared")
    s_acc = Buffer("acc", (8, 128), "float32", "shared")
    s_out = Buffer("outb", (8, 128), "float32", "shared")
    plain = Buffer("plain", (8, 128), "float32", "shared")
    stmts = [
        FillStmt(_region(s_send), 1.0),
        FillStmt(_region(s_acc), 2.0),
        FillStmt(_region(plain), 3.0),
        CommAllGather(_region(s_send), _region(s_recv),
                      direction=2, size=8 * 128),
        CommAllReduce(_region(s_acc), _region(s_out),
                      reduce_type="sum", direction=2, dim=0, clear=False),
    ]
    plan = SimpleNamespace(
        scratch=[s_send, s_recv, s_acc, s_out, plain],
        params=[], grid=[],
        init_stmts=[], main_stmts=stmts, epi_stmts=[])
    promoted = plan_locals(plan)
    for b in (s_send, s_recv, s_acc, s_out):
        assert b.uid not in promoted, \
            f"comm operand {b.name} was SSA-promoted"


def test_par_store_then_load_declines_staging():
    """Writing an any-param window and then loading the same window
    inside one T.Parallel nest must NOT be silently staged (the staged
    read would see the stale pre-nest copy): expect the loud
    HBM-resident codegen error."""
    NB, M, N = 3, 8, 128

    @T.prim_func
    def store_then_load(A: T.Tensor((M, N), "float32"),
                        O: T.Tensor((NB * M, N), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_shared((M, N), "float32")
            T.copy(A, s)
            for k in T.serial(NB):
                for i, j in T.Parallel(M, N):
                    O[k * M + i, j] = s[i, j] * 2.0
                    s[i, j] = O[k * M + i, j] + 1.0
            T.copy(s, O[0, 0])  # conflicting pattern: O residency 'any'

    with pytest.raises(Exception, match="HBM-resident|stayed in HBM"):
        k = tilelang.compile(store_then_load)
        # some paths defer the error to source generation
        k.get_kernel_source()


def test_par_store_then_disjoint_load_still_stages():
    """A read of a window provably DISJOINT (constant block offset) from
    every in-nest store of the same any-param is NOT a hazard: staging
    must proceed (window-granular scan, not uid-granular)."""
    from tilelang_mesh_tpu.transform.plan import plan_kernel
    NB, M, N = 3, 8, 128

    @T.prim_func
    def store_read_disjoint(A: T.Tensor((M, N), "float32"),
                            O: T.Tensor(((NB + 1) * M, N), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_shared((M, N), "float32")
            s2 = T.alloc_shared((M, N), "float32")
            T.copy(A, s)
            for k in T.serial(NB):
                for i, j in T.Parallel(M, N):
                    O[k * M + i, j] = s[i, j] * 2.0
                    s2[i, j] = O[(k + 1) * M + i, j] + 0.0
            T.copy(s2, O[NB * M, 0])
            T.copy(s, O[0, 0])  # conflicting pattern: O residency 'any'

    plan = plan_kernel(store_read_disjoint.func)
    modes = {p.buffer.name: p.mode for p in plan.params}
    assert modes["O"] == "any"
    assert any(b.name.startswith("stage_O") for b in plan.scratch), \
        [b.name for b in plan.scratch]


def test_par_load_then_store_still_stages():
    """The conservative hazard scan must not regress plain
    read-THEN-write nests (pre-nest window is the correct value)."""
    NB, M, N = 3, 8, 128

    @T.prim_func
    def load_then_store(A: T.Tensor((NB * M, N), "float32"),
                        O: T.Tensor((NB * M, N), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_shared((M, N), "float32")
            T.fill(s, 0.0)
            for k in T.serial(NB):
                for i, j in T.Parallel(M, N):
                    s[i, j] = A[k * M + i, j] * 2.0
                for i, j in T.Parallel(M, N):
                    O[k * M + i, j] = s[i, j]
            T.copy(s, O[0, 0])  # force O residency 'any'

    k = tilelang.compile(load_then_store)
    a = np.random.default_rng(0).standard_normal(
        (NB * M, N)).astype(np.float32)
    out = np.empty((NB * M, N), np.float32)
    k(a, out)
    ref = a * 2.0
    ref[:M] = a[2 * M:] * 2.0  # final copy overwrites block 0 with s
    np.testing.assert_allclose(out, ref, rtol=1e-5)


def test_bench_strict_flag_exists():
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run([sys.executable, "bench.py", "--help"],
                       capture_output=True, text=True, timeout=120,
                       cwd=repo)
    assert r.returncode == 0
    assert "--strict" in r.stdout


def test_bench_exit_code_policy():
    """--strict fails the process on any config loss; the default keeps
    partial sweeps green (driver capture mode)."""
    bench = _bench_module()
    assert bench.exit_code(strict=False, n_failed=0) == 0
    assert bench.exit_code(strict=False, n_failed=3) == 0
    assert bench.exit_code(strict=True, n_failed=0) == 0
    assert bench.exit_code(strict=True, n_failed=1) == 2


def test_no_tpu_effect_annotations_warn_once(caplog):
    """API-parity hint functions must not silently accept: they validate
    the builder context and warn once that the hint has no TPU effect."""
    import logging

    import tilelang_mesh_tpu.language.annotations as ann
    ann._warned.discard("set_max_nreg")
    with caplog.at_level(logging.WARNING, logger="tilelang_mesh_tpu"):
        @T.prim_func
        def k(A: T.Tensor((8, 128), "float32"),
              O: T.Tensor((8, 128), "float32")):
            with T.Kernel(1) as bx:
                s = T.alloc_shared((8, 128), "float32")
                T.set_max_nreg(240, 1)
                T.set_max_nreg(240, 1)  # second call must not re-warn
                T.copy(A, s)
                T.copy(s, O)
    warns = [r for r in caplog.records if "set_max_nreg" in r.getMessage()]
    assert len(warns) == 1, f"expected exactly one warning, got {warns}"

    # outside a kernel: loud error, not silent accept
    with pytest.raises(Exception):
        T.set_max_nreg(240, 1)


def _bench_module():
    import importlib.util
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "bench_mod2", os.path.join(repo, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    return bench


def test_bench_child_unknown_config_fast_fail():
    """`--child <unknown>` must emit a parseable error record and exit 3
    without touching any device (the parent's orchestration contract)."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "bench.py", "--child", "no_such_config"],
        capture_output=True, text=True, timeout=120, cwd=repo)
    assert r.returncode == 3
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["config"] == "no_such_config" and "error" in rec


def test_bench_spawn_config_parses_child_record():
    """_spawn_config must surface the child's error record (not hang or
    mis-parse) for a config that fails fast."""
    bench = _bench_module()
    rec, err = bench._spawn_config("no_such_config", q=True, timeout_s=120)
    assert rec is None
    assert "unknown config" in err


def test_bench_vmem_estimator_orders_riskiest_last():
    bench = _bench_module()
    small = bench._gemm_vmem_est(256, 256, 256, 2)
    big = bench._gemm_vmem_est(1024, 2048, 512, 3)
    assert small < big
    # the num_stages term is load-bearing: same blocks, deeper pipeline
    # must estimate strictly larger (it multiplies the operand buffers)
    assert bench._gemm_vmem_est(512, 512, 1024, 3) > \
        bench._gemm_vmem_est(512, 512, 1024, 2)
