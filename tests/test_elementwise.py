"""Elementwise / fill / reduce / cumsum kernel execution tests
(reference testing/python/language coverage)."""

import numpy as np
import pytest

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T
from tilelang_mesh_tpu.utils.tensor import assert_allclose


def test_elementwise_add_direct_global():
    M, N, bm, bn = 256, 256, 128, 128

    @T.prim_func
    def add(A: T.Tensor((M, N), "float32"),
            B: T.Tensor((M, N), "float32"),
            C: T.Tensor((M, N), "float32")):
        with T.Kernel(T.ceildiv(N, bn), T.ceildiv(M, bm)) as (bx, by):
            for i, j in T.Parallel(bm, bn):
                C[by * bm + i, bx * bn + j] = \
                    A[by * bm + i, bx * bn + j] + B[by * bm + i, bx * bn + j]

    k = tilelang.compile(add)
    rng = np.random.default_rng(0)
    a = rng.standard_normal((M, N), dtype=np.float32)
    b = rng.standard_normal((M, N), dtype=np.float32)
    assert_allclose(k(a, b), a + b, rtol=1e-5, atol=1e-5)


def test_cast_kernel():
    M, N = 256, 128

    @T.prim_func
    def cast(A: T.Tensor((M, N), "float32"),
             B: T.Tensor((M, N), "bfloat16")):
        with T.Kernel(1, 1) as (bx, by):
            A_s = T.alloc_shared((M, N), "float32")
            T.copy(A, A_s)
            T.copy(A_s, B[0, 0])

    k = tilelang.compile(cast)
    a = np.random.default_rng(1).standard_normal((M, N), dtype=np.float32)
    out = np.asarray(k(a)).astype(np.float32)
    import jax.numpy as jnp
    ref = np.asarray(jnp.asarray(a, jnp.bfloat16), np.float32)
    assert_allclose(out, ref, rtol=1e-2, atol=1e-2)


def test_exp_softmax_row():
    """Online-softmax building blocks: reduce_max, exp, reduce_sum."""
    M, N = 128, 256

    @T.prim_func
    def softmax(A: T.Tensor((M, N), "float32"),
                B: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            A_s = T.alloc_shared((M, N), "float32")
            mx = T.alloc_fragment((M,), "float32")
            sm = T.alloc_fragment((M,), "float32")
            T.copy(A, A_s)
            T.reduce_max(A_s, mx, dim=1)
            for i, j in T.Parallel(M, N):
                A_s[i, j] = T.exp(A_s[i, j] - mx[i])
            T.reduce_sum(A_s, sm, dim=1)
            for i, j in T.Parallel(M, N):
                A_s[i, j] = A_s[i, j] / sm[i]
            T.copy(A_s, B)

    k = tilelang.compile(softmax)
    a = np.random.default_rng(2).standard_normal((M, N)).astype(np.float32)
    e = np.exp(a - a.max(1, keepdims=True))
    ref = e / e.sum(1, keepdims=True)
    assert_allclose(k(a), ref, rtol=1e-3, atol=1e-3)


def test_fill_and_copy_out():
    @T.prim_func
    def fill(C: T.Tensor((128, 128), "float32")):
        with T.Kernel(1) as bx:
            f = T.alloc_fragment((128, 128), "float32")
            T.fill(f, 3.5)
            T.copy(f, C)

    k = tilelang.compile(fill)
    out = k()
    assert np.allclose(np.asarray(out), 3.5)


def test_cumsum():
    M, N = 64, 128

    @T.prim_func
    def cs(A: T.Tensor((M, N), "float32"),
           B: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_shared((M, N), "float32")
            T.copy(A, s)
            T.cumsum(s, s, dim=1)
            T.copy(s, B)

    k = tilelang.compile(cs)
    a = np.random.default_rng(3).standard_normal((M, N)).astype(np.float32)
    assert_allclose(k(a), np.cumsum(a, axis=1), rtol=1e-4, atol=1e-4)


def test_reduce_variants():
    M, N = 64, 128
    cases = {
        "sum": lambda a: a.sum(1),
        "max": lambda a: a.max(1),
        "min": lambda a: a.min(1),
        "abssum": lambda a: np.abs(a).sum(1),
        "absmax": lambda a: np.abs(a).max(1),
    }
    for kind, ref in cases.items():
        @T.prim_func
        def red(A: T.Tensor((M, N), "float32"),
                B: T.Tensor((M,), "float32")):
            with T.Kernel(1) as bx:
                s = T.alloc_shared((M, N), "float32")
                o = T.alloc_fragment((M,), "float32")
                T.copy(A, s)
                T.reduce(s, o, kind, dim=1)
                T.copy(o, B)

        k = tilelang.compile(red)
        a = np.random.default_rng(4).standard_normal((M, N)) \
            .astype(np.float32)
        assert_allclose(k(a), ref(a), rtol=1e-4, atol=1e-4), kind


def test_transpose_via_parallel():
    M, N = 128, 64

    @T.prim_func
    def tr(A: T.Tensor((M, N), "float32"),
           B: T.Tensor((N, M), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_shared((M, N), "float32")
            d = T.alloc_shared((N, M), "float32")
            T.copy(A, s)
            for i, j in T.Parallel(N, M):
                d[i, j] = s[j, i]
            T.copy(d, B)

    k = tilelang.compile(tr)
    a = np.random.default_rng(5).standard_normal((M, N)).astype(np.float32)
    assert_allclose(k(a), a.T, rtol=1e-6, atol=1e-6)


def test_scalar_var_and_if():
    M = 128

    @T.prim_func
    def k1(A: T.Tensor((M, M), "float32"),
           B: T.Tensor((M, M), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_shared((M, M), "float32")
            T.copy(A, s)
            # grid-dependent predicated execution
            for i, j in T.Parallel(M, M):
                s[i, j] = T.if_then_else(bx == 0, s[i, j] * 2.0, s[i, j])
            T.copy(s, B)

    k = tilelang.compile(k1)
    a = np.random.default_rng(6).standard_normal((M, M)).astype(np.float32)
    assert_allclose(k(a), a * 2.0, rtol=1e-6, atol=1e-6)
