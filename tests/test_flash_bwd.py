"""FlashAttention backward tile kernels vs dense-AD reference."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tilelang_mesh_tpu.ops.flash_attention import (_reference_attention,
                                                   flash_attention)
from tilelang_mesh_tpu.utils.tensor import assert_allclose


@pytest.mark.parametrize("causal", [False, True])
def test_kernel_backward_matches_dense_ad(causal):
    B, H, S, D = 1, 2, 256, 64
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    g = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)

    def loss_kernel(q, k, v):
        return jnp.vdot(flash_attention(q, k, v, causal=causal,
                                        backward="kernel"), g)

    def loss_ref(q, k, v):
        return jnp.vdot(_reference_attention(
            q, k, v, causal, 1.0 / np.sqrt(D)).astype(jnp.float32), g)

    gk = jax.grad(loss_kernel, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gk, gr, "qkv"):
        assert_allclose(np.asarray(a), np.asarray(b), rtol=3e-2, atol=3e-1)


def test_kernel_backward_rect():
    B, H, Sq, Sk, D = 1, 1, 128, 384, 64
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.standard_normal((B, H, Sq, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, Sk, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, Sk, D)), jnp.float32)

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, backward="kernel") ** 2)

    def loss_ref(q, k, v):
        o = _reference_attention(q, k, v, False, 1.0 / np.sqrt(D))
        return jnp.sum(o.astype(jnp.float32) ** 2)

    gk = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gk, gr):
        assert_allclose(np.asarray(a), np.asarray(b), rtol=3e-2, atol=3e-1)
