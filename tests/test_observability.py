"""Observability subsystem: tracer, exporters, pipeline instrumentation.

Covers the ISSUE-1 checklist: span nesting (thread-local), disabled-mode
no-op behavior, cache hit/miss counters across a compile -> recompile
cycle, collective byte accounting for a ``T.comm.all_reduce`` kernel,
Chrome-trace / JSONL export round-trips, the ``tools/analyzer.py
--trace`` breakdown, and the acceptance smoke: ``TL_TPU_TRACE=1`` around
a real GEMM compile+run yields a valid Chrome trace with all five
lowering phases and a cache event — and changes no numerics.
"""

import json
import threading

import numpy as np
import pytest

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T
from tilelang_mesh_tpu import observability as obs
from tilelang_mesh_tpu.observability import tracer as tr


@pytest.fixture(autouse=True)
def _fresh_tracer():
    """Every test starts from an empty process tracer."""
    obs.reset()
    yield
    obs.reset()


@pytest.fixture()
def traced(monkeypatch, tmp_path):
    """Tracing ON with hermetic cache/trace dirs (a shared disk cache
    would turn this test's compiles into disk hits and skip the
    lowering phases under test)."""
    monkeypatch.setenv("TL_TPU_TRACE", "1")
    monkeypatch.setenv("TL_TPU_CACHE_DIR", str(tmp_path / "kernels"))
    monkeypatch.setenv("TL_TPU_TRACE_DIR", str(tmp_path / "trace"))
    tilelang.clear_cache()
    yield tmp_path
    tilelang.clear_cache()


def _scale_func(mult=2.0, M=64, N=128):
    @T.prim_func
    def scale(A: T.Tensor((M, N), "float32"),
              B: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_shared((M, N), "float32")
            T.copy(A, s)
            for i, j in T.Parallel(M, N):
                s[i, j] = s[i, j] * mult
            T.copy(s, B)
    return scale


# ---------------------------------------------------------------------------
# tracer core
# ---------------------------------------------------------------------------

class TestTracerCore:
    def test_span_nesting_depth_and_order(self, monkeypatch):
        monkeypatch.setenv("TL_TPU_TRACE", "1")
        t = obs.get_tracer()
        with t.span("outer", "test"):
            with t.span("inner", "test", detail=1):
                pass
        evs = [e for e in t.events() if e["type"] == "span"]
        # inner finishes (and records) first
        assert [e["name"] for e in evs] == ["inner", "outer"]
        inner, outer = evs
        assert inner["depth"] == 1 and outer["depth"] == 0
        assert inner["tid"] == outer["tid"]
        assert inner["attrs"] == {"detail": 1}
        assert outer["dur_us"] >= inner["dur_us"] >= 0
        # the child started no earlier than the parent
        assert inner["ts_us"] >= outer["ts_us"]

    def test_nesting_is_thread_local(self, monkeypatch):
        monkeypatch.setenv("TL_TPU_TRACE", "1")
        t = obs.get_tracer()
        depths = {}

        def worker():
            with t.span("w", "test") as sp:
                depths["worker"] = sp.depth

        with t.span("main", "test") as sp:
            depths["main"] = sp.depth
            th = threading.Thread(target=worker)
            th.start()
            th.join()
        # the worker's span must NOT nest under main's open span
        assert depths == {"main": 0, "worker": 0}
        tids = {e["name"]: e["tid"] for e in t.events()}
        assert tids["w"] != tids["main"]

    def test_span_records_error_and_propagates(self, monkeypatch):
        monkeypatch.setenv("TL_TPU_TRACE", "1")
        t = obs.get_tracer()
        with pytest.raises(ValueError):
            with t.span("boom", "test"):
                raise ValueError("bad plan")
        ev = t.events()[-1]
        assert ev["name"] == "boom"
        assert "ValueError: bad plan" in ev["attrs"]["error"]

    def test_disabled_mode_is_noop(self, monkeypatch):
        monkeypatch.delenv("TL_TPU_TRACE", raising=False)
        t = obs.get_tracer()
        s1 = t.span("a", "test")
        s2 = t.span("b", "test")
        # one shared null instance: no allocation per disabled call site
        assert s1 is s2
        with s1 as sp:
            sp.set(key="dropped")
        t.event("instant", "test")
        assert t.events() == []
        # counters stay live even when untraced
        t.inc("still.counted")
        assert t.counters()["still.counted"] == 1

    def test_event_cap_evicts_oldest_and_counts(self, monkeypatch):
        monkeypatch.setenv("TL_TPU_TRACE", "1")
        monkeypatch.setenv("TL_TPU_TRACE_MAX_EVENTS", "3")
        t = obs.get_tracer()
        for i in range(10):
            t.event(f"e{i}", "test")
        evs = t.events()
        assert len(evs) == 3
        # ring semantics: the NEWEST events survive (a long serving
        # soak keeps its most recent history), the oldest are evicted
        assert [e["name"] for e in evs] == ["e7", "e8", "e9"]
        assert t.counters()["trace.dropped"] == 7

    def test_reset_clears_state(self, monkeypatch):
        monkeypatch.setenv("TL_TPU_TRACE", "1")
        t = obs.get_tracer()
        t.event("x", "test")
        t.inc("c")
        obs.reset()
        assert t.events() == [] and t.counters() == {}

    def test_span_straddling_reset_is_dropped(self, monkeypatch):
        """A span opened before reset() (e.g. on an abandoned watchdog
        thread) must not land in the post-reset event list with a stale
        clock origin."""
        monkeypatch.setenv("TL_TPU_TRACE", "1")
        t = obs.get_tracer()
        stale = t.span("stale", "test")
        stale.__enter__()
        obs.reset()
        with t.span("fresh", "test"):
            pass
        stale.__exit__(None, None, None)
        assert [e["name"] for e in t.events()] == ["fresh"]
        assert all(e["dur_us"] >= 0 for e in t.events())

    def test_labelled_counters_render(self):
        t = obs.get_tracer()
        t.inc("comm.ops", op="all_reduce")
        t.inc("comm.ops", 2, op="broadcast")
        c = t.counters()
        assert c["comm.ops{op=all_reduce}"] == 1
        assert c["comm.ops{op=broadcast}"] == 2


# ---------------------------------------------------------------------------
# compile pipeline instrumentation
# ---------------------------------------------------------------------------

PHASES = ("canonicalize", "checks", "plan", "codegen", "artifact")


class TestPipelineInstrumentation:
    def test_cache_counters_across_compile_recompile(self, traced):
        f = _scale_func(mult=5.0)
        tilelang.compile(f, target="cpu")
        c = obs.get_tracer().counters()
        assert c["cache.memory.miss"] == 1
        assert c["cache.disk.miss"] == 1
        assert c["cache.build"] == 1
        assert c.get("cache.artifact_bytes_written", 0) > 0

        tilelang.compile(f, target="cpu")          # -> memory hit
        c = obs.get_tracer().counters()
        assert c["cache.memory.hit"] == 1

        tilelang.clear_cache()                     # memory only
        tilelang.compile(f, target="cpu")          # -> disk hit
        c = obs.get_tracer().counters()
        assert c["cache.memory.miss"] == 2
        assert c["cache.disk.hit"] == 1
        assert c["cache.build"] == 1               # never rebuilt
        assert c.get("cache.artifact_bytes_read", 0) > 0

        summ = obs.metrics_summary()
        assert summ["cache"]["memory_hit_rate"] == pytest.approx(1 / 3,
                                                                 abs=1e-3)
        assert summ["cache"]["disk_hit_rate"] == pytest.approx(1 / 2)

    def test_lowering_phase_spans_recorded(self, traced):
        tilelang.compile(_scale_func(mult=7.0), target="cpu")
        spans = [e for e in obs.get_tracer().events()
                 if e["type"] == "span"]
        names = [e["name"] for e in spans]
        for ph in PHASES:
            assert names.count(ph) == 1, f"phase {ph} missing"
        by_name = {e["name"]: e for e in spans}
        root = by_name["lower"]
        assert root["attrs"]["kernel"] == "scale"
        assert root["attrs"]["target"] == "cpu"
        for ph in PHASES:
            assert by_name[ph]["depth"] > root["depth"]

    def test_jit_callsite_counters(self, traced):
        @tilelang.jit
        def factory(mult):
            return _scale_func(mult=mult)

        factory(2.0)
        factory(2.0)
        factory(3.0)
        c = obs.get_tracer().counters()
        assert c["jit.callsite.miss"] == 2
        assert c["jit.callsite.hit"] == 1

    def test_lazy_jit_bucket_events_and_counters(self, traced):
        M = T.dynamic("m")
        N, BK = 128, 64

        @tilelang.lazy_jit(out_idx=[1], dynamic_bucket=BK)
        def scale(A: T.Tensor((M, N), "float32"),
                  B: T.Tensor((M, N), "float32")):
            with T.Kernel(T.ceildiv(M, BK)) as bx:
                s = T.alloc_shared((BK, N), "float32")
                T.copy(A[bx * BK, 0], s)
                for i, j in T.Parallel(BK, N):
                    s[i, j] = s[i, j] * 2.0
                T.copy(s, B[bx * BK, 0])

        rng = np.random.default_rng(0)
        for m in (50, 64, 30):            # one 64 bucket -> one compile
            a = rng.standard_normal((m, N), dtype=np.float32)
            np.testing.assert_allclose(np.asarray(scale(a)), a * 2,
                                       rtol=1e-5)
        c = obs.get_tracer().counters()
        assert c["jit.lazy.miss"] == 1
        assert c["jit.lazy.hit"] == 2
        evs = [e for e in obs.get_tracer().events()
               if e["type"] == "event" and e["name"] == "jit.lazy_bucket"]
        assert len(evs) == 3
        assert evs[0]["attrs"]["bucket"] == BK
        (d0,) = evs[0]["attrs"]["dims"]
        assert (d0["dim"], d0["true"], d0["padded"]) == ("m", 50, 64)
        spec = [e for e in obs.get_tracer().events()
                if e["type"] == "span"
                and e["name"] == "jit.lazy_specialize"]
        assert len(spec) == 1 and spec[0]["attrs"]["shapes"] == {"m": 64}

    def test_autotune_trial_spans(self, traced):
        def factory(block_M=32):
            M, N = 64, 128
            bm = block_M

            @T.prim_func
            def k(A: T.Tensor((M, N), "float32"),
                  B: T.Tensor((M, N), "float32")):
                with T.Kernel(T.ceildiv(M, bm)) as bx:
                    s = T.alloc_shared((bm, N), "float32")
                    T.copy(A[bx * bm, 0], s)
                    for i, j in T.Parallel(bm, N):
                        s[i, j] = s[i, j] + 1.0
                    T.copy(s, B[bx * bm, 0])
            return tilelang.compile(k, target="cpu")

        tuned = tilelang.autotune(configs=[{"block_M": 32},
                                           {"block_M": 64}],
                                  warmup=1, rep=2,
                                  cache_results=False)(factory)
        tuned()
        spans = [e for e in obs.get_tracer().events()
                 if e["type"] == "span" and e["name"] == "autotune.trial"]
        assert len(spans) == 2
        assert all(s["attrs"]["outcome"] == "ok" for s in spans)
        assert all(s["attrs"]["latency_ms"] > 0 for s in spans)
        runs = [e for e in obs.get_tracer().events()
                if e["type"] == "span" and e["name"] == "autotune.run"]
        assert len(runs) == 1 and "best_config" in runs[0]["attrs"]


# ---------------------------------------------------------------------------
# collective accounting
# ---------------------------------------------------------------------------

MESH = (2, 4)


def _allreduce_artifact():
    from tilelang_mesh_tpu.parallel import mesh_config
    nrow, ncol = MESH
    with mesh_config(*MESH):
        @T.prim_func
        def k(A: T.MeshTensor((nrow * ncol * 8, 128),
                              T.MeshShardingPolicy(cross_mesh_dim=0),
                              MESH, "float32"),
              B: T.MeshTensor((nrow * ncol * 8, 1),
                              T.MeshShardingPolicy(cross_mesh_dim=0),
                              MESH, "float32")):
            with T.Kernel(1) as bx:
                x = T.alloc_shared((8, 128), "float32")
                out = T.alloc_shared((8, 1), "float32")
                T.copy(A, x)
                T.comm.all_reduce(x, out, "sum", "all")
                T.copy(out, B)
        return tilelang.lower(k, target=f"cpu-mesh[{nrow}x{ncol}]")


class TestCollectiveAccounting:
    def test_all_reduce_bytes_and_axis(self, traced):
        art = _allreduce_artifact()
        recs = art.attrs["collectives"]
        assert len(recs) == 1
        rec = recs[0]
        assert rec["op"] == "allreduce"
        assert rec["axis"] == "x,y"
        assert rec["reduce_type"] == "sum"
        # per-hop wire payload is the locally-reduced OUT chunk:
        # 8x1 f32 = 32 bytes
        assert rec["payload_bytes"] == 32
        assert rec["hops"] >= 1
        assert rec["wire_bytes"] == rec["payload_bytes"] * rec["hops"]
        # ... and the same record landed in the tracer
        evs = [e for e in obs.get_tracer().events()
               if e["type"] == "event" and e["name"] == "comm.collective"]
        assert len(evs) == 1 and evs[0]["attrs"]["op"] == "allreduce"
        c = obs.get_tracer().counters()
        assert c["comm.ops{op=allreduce}"] == 1
        assert c["comm.bytes{op=allreduce}"] == rec["wire_bytes"]
        assert c["comm.emitted{op=all_reduce}"] == 1
        summ = obs.metrics_summary()
        assert summ["collectives"]["ops"] == 1
        assert summ["collectives"]["bytes"] == rec["wire_bytes"]

    def test_accounting_works_untraced(self, monkeypatch, tmp_path):
        # counters (but no events) even with tracing off
        monkeypatch.delenv("TL_TPU_TRACE", raising=False)
        monkeypatch.setenv("TL_TPU_CACHE_DIR", str(tmp_path / "kernels"))
        art = _allreduce_artifact()
        assert art.attrs["collectives"][0]["wire_bytes"] > 0
        assert obs.get_tracer().counters()["comm.ops{op=allreduce}"] == 1
        assert obs.get_tracer().events() == []


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------

class TestExporters:
    def test_chrome_trace_round_trip(self, traced):
        tilelang.compile(_scale_func(mult=9.0), target="cpu")
        path = obs.write_chrome_trace(traced / "t.trace.json")
        loaded = json.loads(path.read_text())     # strict JSON
        names = {e["name"] for e in loaded["traceEvents"]}
        for ph in PHASES:
            assert ph in names
        phs = {e["ph"] for e in loaded["traceEvents"]}
        assert "X" in phs and "C" in phs
        for e in loaded["traceEvents"]:
            if e["ph"] == "X":
                assert e["ts"] >= 0 and e["dur"] >= 0
                assert isinstance(e["pid"], int)
                assert isinstance(e["tid"], int)

    def test_jsonl_round_trip(self, traced):
        tilelang.compile(_scale_func(mult=11.0), target="cpu")
        path = obs.write_jsonl(traced / "t.jsonl")
        recs = obs.read_jsonl(path)
        types = {r["type"] for r in recs}
        assert types == {"span", "event", "counter"}
        span_names = [r["name"] for r in recs if r["type"] == "span"]
        for ph in PHASES:
            assert ph in span_names
        counters = {r["name"]: r["value"] for r in recs
                    if r["type"] == "counter"}
        assert counters["cache.build"] == 1

    def test_prometheus_snapshot(self, traced):
        tilelang.compile(_scale_func(mult=13.0), target="cpu")
        text = obs.to_prometheus_text()
        assert "# TYPE tl_tpu_cache_build counter" in text
        assert "tl_tpu_cache_build 1" in text
        assert "tl_tpu_span_plan_seconds_count 1" in text

    def test_prometheus_one_type_line_per_metric(self):
        t = obs.get_tracer()
        t.inc("comm.ops", op="broadcast")
        t.inc("comm.ops", op="allreduce")
        text = obs.to_prometheus_text()
        # exposition format: at most ONE TYPE line per metric name
        assert text.count("# TYPE tl_tpu_comm_ops counter") == 1
        assert 'tl_tpu_comm_ops{op="broadcast"} 1' in text
        assert 'tl_tpu_comm_ops{op="allreduce"} 1' in text

    def test_exporters_empty_tracer(self):
        assert obs.to_chrome_trace()["traceEvents"] == []
        assert obs.to_jsonl() == ""
        assert obs.to_prometheus_text() == ""
        summ = obs.metrics_summary()
        assert summ["spans"] == {} and summ["counters"] == {}

    def test_json_safe_attrs_never_break_export(self, monkeypatch):
        monkeypatch.setenv("TL_TPU_TRACE", "1")
        t = obs.get_tracer()
        t.event("weird", "test", obj=object(), nan=float("nan"),
                tup=(1, 2))
        blob = json.dumps(obs.to_chrome_trace())   # must not raise
        args = json.loads(blob)["traceEvents"][0]["args"]
        assert args["tup"] == [1, 2]
        assert isinstance(args["obj"], str)
        assert isinstance(args["nan"], str)        # no bare NaN token


# ---------------------------------------------------------------------------
# analyzer --trace
# ---------------------------------------------------------------------------

class TestTraceAnalyzer:
    def test_trace_report_breakdown(self, traced, capsys):
        from tilelang_mesh_tpu.tools.analyzer import main
        tilelang.compile(_scale_func(mult=17.0), target="cpu")
        path = obs.write_jsonl(traced / "t.jsonl")
        assert main(["--trace", str(path)]) == 0
        out = capsys.readouterr().out
        assert "compile-time breakdown by lowering phase" in out
        for ph in PHASES:
            assert ph in out
        assert "cache counters:" in out
        assert "cache.build" in out

    def test_trace_report_collectives_and_empty(self, traced, capsys,
                                                tmp_path):
        from tilelang_mesh_tpu.tools.analyzer import format_trace_report
        _allreduce_artifact()
        recs = obs.read_jsonl(obs.write_jsonl(traced / "m.jsonl"))
        out = format_trace_report(recs)
        assert "collectives (static accounting)" in out
        assert "allreduce" in out
        # an empty trace explains itself instead of crashing
        empty = tmp_path / "e.jsonl"
        empty.write_text("")
        assert "no lowering-phase spans" in format_trace_report(
            obs.read_jsonl(empty))


# ---------------------------------------------------------------------------
# acceptance smoke: TL_TPU_TRACE=1 around a real kernel changes nothing
# ---------------------------------------------------------------------------

class TestTraceSmoke:
    def test_gemm_compile_run_under_trace(self, traced):
        """The ISSUE-1 acceptance shape: tracing a GEMM compile+run
        yields a valid Chrome trace with all five lowering phases and a
        cache event, and the kernel's numerics are untouched."""
        M = N = K = 128

        @T.prim_func
        def gemm(A: T.Tensor((M, K), "float32"),
                 B: T.Tensor((K, N), "float32"),
                 C: T.Tensor((M, N), "float32")):
            with T.Kernel(1) as bx:
                a = T.alloc_shared((M, K), "float32")
                b = T.alloc_shared((K, N), "float32")
                c = T.alloc_fragment((M, N), "float32")
                T.copy(A, a)
                T.copy(B, b)
                T.clear(c)
                T.gemm(a, b, c)
                T.copy(c, C)

        k = tilelang.compile(gemm, target="cpu")
        rng = np.random.default_rng(0)
        a = rng.standard_normal((M, K)).astype(np.float32)
        b = rng.standard_normal((K, N)).astype(np.float32)
        out = np.asarray(k(a, b))
        np.testing.assert_allclose(out, a @ b, rtol=1e-5, atol=1e-5)

        trace = obs.to_chrome_trace()
        json.loads(json.dumps(trace))              # valid strict JSON
        names = [e["name"] for e in trace["traceEvents"]]
        for ph in PHASES:
            assert ph in names
        cache_events = [e for e in trace["traceEvents"]
                        if e.get("cat") == "cache"]
        assert cache_events, "no cache event in the trace"

    def test_trace_flag_does_not_change_results(self, monkeypatch,
                                                tmp_path):
        """Same kernel, tracing off vs on: identical outputs (the
        fast 'TL_TPU_TRACE=1 adds no failures' tier-1 smoke)."""
        monkeypatch.setenv("TL_TPU_CACHE_DIR", str(tmp_path / "kernels"))
        rng = np.random.default_rng(1)
        x = rng.standard_normal((64, 128)).astype(np.float32)

        monkeypatch.delenv("TL_TPU_TRACE", raising=False)
        tilelang.clear_cache()
        k_off = tilelang.compile(_scale_func(mult=2.5), target="cpu")
        out_off = np.asarray(k_off(x))

        monkeypatch.setenv("TL_TPU_TRACE", "1")
        tilelang.clear_cache(disk=True)   # force a full traced rebuild
        k_on = tilelang.compile(_scale_func(mult=2.5), target="cpu")
        out_on = np.asarray(k_on(x))
        np.testing.assert_array_equal(out_off, out_on)
        assert [e for e in obs.get_tracer().events()
                if e["name"] == "lower"]
