"""Resilience-subsystem chaos suite (docs/robustness.md).

Everything here is DETERMINISTIC: every fault clause is seeded, so the
suite is tier-1-safe. The ``chaos`` marker tags the end-to-end sweep that
arms several sites at once — still seeded, but the heaviest test in the
file.
"""

import concurrent.futures
import json
import os
import time

import numpy as np
import pytest

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T
from tilelang_mesh_tpu.cache.kernel_cache import (
    ARTIFACT_FILE, KERNEL_SOURCE_FILE, QUARANTINE_DIR, KernelCache, _CACHE)
from tilelang_mesh_tpu.env import env
from tilelang_mesh_tpu.observability import get_tracer
from tilelang_mesh_tpu.resilience import (
    CircuitBreaker, DeterministicError, FaultSpec, InjectedFault,
    RetryPolicy, TLError, TLTimeoutError, TransientError, classify,
    error_signature, inject, maybe_fail, parse_fault_spec, retry_call)


@pytest.fixture(autouse=True)
def _hermetic(tmp_path, monkeypatch):
    """Fresh cache dirs + clean tracer per test: chaos must not leak."""
    monkeypatch.setenv("TL_TPU_CACHE_DIR", str(tmp_path / "kernels"))
    monkeypatch.setenv("TL_TPU_AUTOTUNE_CACHE_DIR", str(tmp_path / "tune"))
    monkeypatch.setenv("TL_TPU_RETRY_BASE_MS", "1")
    monkeypatch.setenv("TL_TPU_RETRY_MAX_MS", "5")
    monkeypatch.delenv("TL_TPU_FAULTS", raising=False)
    _CACHE.clear()
    get_tracer().reset()
    yield
    _CACHE.clear()
    get_tracer().reset()


_uniq = iter(range(10_000))


def _scale_func(mult):
    """A fresh prim_func per mult value (distinct cache keys)."""
    M, N = 64, 128

    @T.prim_func
    def scale(A: T.Tensor((M, N), "float32"),
              B: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_shared((M, N), "float32")
            T.copy(A, s)
            for i, j in T.Parallel(M, N):
                s[i, j] = s[i, j] * mult
            T.copy(s, B)
    return scale


def _run_scale(kernel, mult):
    a = np.arange(64 * 128, dtype=np.float32).reshape(64, 128) / 100
    np.testing.assert_allclose(np.asarray(kernel(a)), a * mult, rtol=1e-6)


# ---------------------------------------------------------------------------
# error taxonomy
# ---------------------------------------------------------------------------

class TestErrors:
    def test_classify_taxonomy(self):
        assert classify(TransientError("x")) == "transient"
        assert classify(DeterministicError("x")) == "deterministic"
        assert classify(TLTimeoutError("x")) == "timeout"
        assert classify(OSError("disk")) == "transient"
        assert classify(concurrent.futures.TimeoutError()) == "timeout"
        assert classify(TypeError("bad")) == "deterministic"
        assert classify(ValueError("bad")) == "deterministic"

    def test_timeout_error_is_futures_timeout(self):
        # pre-taxonomy callers catch concurrent.futures.TimeoutError
        assert isinstance(TLTimeoutError("t"), concurrent.futures.TimeoutError)

    def test_error_carries_site_and_phase(self):
        e = TransientError("boom", site="autotune.trial", phase="lower.plan")
        assert "autotune.trial" in str(e) and "lower.plan" in str(e)
        assert isinstance(e, TLError)

    def test_error_signature_buckets(self):
        a = error_signature(ValueError("same message"))
        b = error_signature(ValueError("same message"))
        c = error_signature(TypeError("same message"))
        assert a == b and a != c
        long = error_signature(ValueError("x" * 500))
        assert len(long) < 120


# ---------------------------------------------------------------------------
# fault spec grammar + injection
# ---------------------------------------------------------------------------

class TestFaultSpec:
    def test_parse_full_grammar(self):
        specs = parse_fault_spec(
            "cache.disk.write:p=0.3:seed=7:kind=corrupt;"
            "lower.*:kind=deterministic:times=2; autotune.trial")
        assert len(specs) == 3
        assert specs[0].p == 0.3 and specs[0].seed == 7
        assert specs[0].kind == "corrupt"
        assert specs[1].matches("lower.plan")
        assert specs[1].matches("lower.codegen")
        assert not specs[1].matches("jit.compile")
        assert specs[1].times == 2
        assert specs[2].p == 1.0 and specs[2].kind == "transient"

    @pytest.mark.parametrize("bad", [
        "site:p=2.0", "site:kind=nonsense", "site:frobnicate=1",
        "site:p", ":p=0.5",
    ])
    def test_parse_rejects_malformed(self, bad):
        with pytest.raises(ValueError):
            parse_fault_spec(bad)

    def test_seeded_determinism(self):
        fires1 = [FaultSpec("s", p=0.5, seed=42).should_fire()
                  or False for _ in range(1)]
        a = FaultSpec("s", p=0.5, seed=42)
        b = FaultSpec("s", p=0.5, seed=42)
        seq_a = [a.should_fire() for _ in range(50)]
        seq_b = [b.should_fire() for _ in range(50)]
        assert seq_a == seq_b
        assert any(seq_a) and not all(seq_a)
        assert fires1 is not None  # silence lint on the warmup draw

    def test_times_limit(self):
        s = FaultSpec("s", p=1.0, times=2)
        assert [s.should_fire() for _ in range(5)] == \
            [True, True, False, False, False]

    def test_inject_scope_raises_and_counts(self):
        with inject("autotune.trial", times=1) as spec:
            with pytest.raises(InjectedFault):
                maybe_fail("autotune.trial")
            maybe_fail("autotune.trial")   # times exhausted
        assert spec._fired == 1
        maybe_fail("autotune.trial")       # scope closed: inert

    def test_env_spec_arms_sites(self, monkeypatch):
        monkeypatch.setenv("TL_TPU_FAULTS", "lower.plan:kind=deterministic")
        with pytest.raises(DeterministicError):
            maybe_fail("lower.plan")
        maybe_fail("lower.codegen")        # unmatched site: inert

    def test_faults_unset_means_zero_events(self, monkeypatch):
        """The satellite contract: no TL_TPU_FAULTS, no injected events —
        even with tracing on and a real compile underway."""
        monkeypatch.setenv("TL_TPU_TRACE", "1")
        get_tracer().reset()
        k = tilelang.compile(_scale_func(1.25))
        _run_scale(k, 1.25)
        evs = [e for e in get_tracer().events()
               if e.get("name") == "fault.injected"]
        assert evs == []
        assert "fault.injected" not in " ".join(get_tracer().counters())


# ---------------------------------------------------------------------------
# retry / backoff / circuit breaker
# ---------------------------------------------------------------------------

class TestRetry:
    def _policy(self):
        return RetryPolicy(max_attempts=3, base_delay_s=0.0,
                           max_delay_s=0.0)

    def test_transient_retries_then_succeeds(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise TransientError("flaky")
            return "ok"
        assert retry_call(flaky, site="t", policy=self._policy()) == "ok"
        assert len(calls) == 3

    def test_transient_exhausts_attempts(self):
        calls = []

        def always():
            calls.append(1)
            raise TransientError("never")
        with pytest.raises(TransientError):
            retry_call(always, site="t", policy=self._policy())
        assert len(calls) == 3

    def test_deterministic_never_retries(self):
        calls = []

        def broken():
            calls.append(1)
            raise TypeError("broken kernel")
        with pytest.raises(TypeError):
            retry_call(broken, site="t", policy=self._policy())
        assert len(calls) == 1

    def test_timeout_retries_exactly_once(self):
        calls = []

        def wedged():
            calls.append(1)
            raise TLTimeoutError("wedged")
        with pytest.raises(TLTimeoutError):
            retry_call(wedged, site="t", policy=self._policy())
        assert len(calls) == 2

    def test_backoff_is_exponential_and_capped(self):
        p = RetryPolicy(max_attempts=5, base_delay_s=0.1, max_delay_s=0.3,
                        jitter=0.0)
        assert p.delay_s(0) == pytest.approx(0.1)
        assert p.delay_s(1) == pytest.approx(0.2)
        assert p.delay_s(3) == pytest.approx(0.3)   # capped

    def test_breaker_opens_at_threshold(self):
        br = CircuitBreaker(threshold=3)
        sig = "ValueError:bad tile"
        assert br.record_failure(sig) is False
        assert br.record_failure(sig) is False
        assert not br.is_open(sig)
        assert br.record_failure(sig) is True    # trip reported once
        assert br.is_open(sig)
        assert not br.is_open("other")
        br.reset(sig)
        assert not br.is_open(sig)

    def test_open_breaker_suppresses_retries(self):
        # the signature is already known-deterministic (breaker open):
        # a transient wearing the same signature gets no retries
        br = CircuitBreaker(threshold=1)
        br.record_failure("TransientError:same failure")
        calls = []

        def flaky():
            calls.append(1)
            raise TransientError("same failure")
        with pytest.raises(TransientError):
            retry_call(flaky, site="t", policy=self._policy(), breaker=br)
        assert len(calls) == 1

    def test_transients_do_not_feed_breaker(self):
        # retry exists to absorb transients; they must never open the
        # circuit, no matter how many identical ones occur
        br = CircuitBreaker(threshold=2)
        calls = []

        def flaky():
            calls.append(1)
            raise TransientError("same failure")
        with pytest.raises(TransientError):
            retry_call(flaky, site="t", policy=self._policy(), breaker=br)
        assert len(calls) == 3     # full retry budget used
        assert not br.is_open("TransientError:same failure")

    def test_deterministic_failures_feed_breaker(self):
        br = CircuitBreaker(threshold=2)

        def broken():
            raise TypeError("bad tile")
        for _ in range(2):
            with pytest.raises(TypeError):
                retry_call(broken, site="t", policy=self._policy(),
                           breaker=br)
        assert br.is_open("TypeError:bad tile")


# ---------------------------------------------------------------------------
# crash-safe cache
# ---------------------------------------------------------------------------

def _disk_entries():
    return [p for p in env.cache_dir().iterdir()
            if p.is_dir() and not p.name.startswith(".")]


def _quarantined():
    q = env.cache_dir() / QUARANTINE_DIR
    return list(q.iterdir()) if q.exists() else []


class TestCacheResilience:
    def test_artifact_has_checksum_and_roundtrips(self):
        k1 = tilelang.compile(_scale_func(2.5))
        (entry,) = _disk_entries()
        meta = json.loads((entry / ARTIFACT_FILE).read_text())
        assert len(meta["source_sha256"]) == 64
        _CACHE.clear()
        k2 = tilelang.compile(_scale_func(2.5))
        assert k2 is not k1
        assert k2.get_kernel_source() == k1.get_kernel_source()
        _run_scale(k2, 2.5)

    def test_no_tmp_files_left_behind(self):
        tilelang.compile(_scale_func(2.75))
        (entry,) = _disk_entries()
        assert not [p for p in entry.iterdir() if ".tmp." in p.name]

    def test_corrupt_source_quarantined_and_rebuilt(self):
        tilelang.compile(_scale_func(3.5))
        (entry,) = _disk_entries()
        (entry / KERNEL_SOURCE_FILE).write_text("truncated garb")
        _CACHE.clear()
        before = get_tracer().counters().get("cache.quarantined", 0)
        k = tilelang.compile(_scale_func(3.5))
        _run_scale(k, 3.5)
        assert len(_quarantined()) == 1
        assert get_tracer().counters()["cache.quarantined"] == before + 1
        # the rebuilt entry is fresh and valid
        _CACHE.clear()
        _run_scale(tilelang.compile(_scale_func(3.5)), 3.5)

    def test_truncated_meta_quarantined(self):
        tilelang.compile(_scale_func(4.5))
        (entry,) = _disk_entries()
        meta_text = (entry / ARTIFACT_FILE).read_text()
        (entry / ARTIFACT_FILE).write_text(meta_text[: len(meta_text) // 2])
        _CACHE.clear()
        _run_scale(tilelang.compile(_scale_func(4.5)), 4.5)
        assert len(_quarantined()) == 1

    def test_incomplete_entry_quarantined(self):
        tilelang.compile(_scale_func(5.5))
        (entry,) = _disk_entries()
        (entry / ARTIFACT_FILE).unlink()   # torn write: no commit point
        _CACHE.clear()
        _run_scale(tilelang.compile(_scale_func(5.5)), 5.5)
        assert len(_quarantined()) == 1

    def test_repeated_corruption_keeps_both_quarantines(self):
        for _ in range(2):
            tilelang.compile(_scale_func(6.5))
            (entry,) = _disk_entries()
            (entry / KERNEL_SOURCE_FILE).write_text("bad")
            _CACHE.clear()
            tilelang.compile(_scale_func(6.5))
            (entry,) = _disk_entries()
            (entry / KERNEL_SOURCE_FILE).write_text("bad")
            _CACHE.clear()
        tilelang.compile(_scale_func(6.5))
        assert len(_quarantined()) >= 2

    def test_write_fault_degrades_to_uncached(self):
        with inject("cache.disk.write", kind="oserror"):
            k = tilelang.compile(_scale_func(7.5))
        _run_scale(k, 7.5)
        assert _disk_entries() == []      # nothing cached…
        assert get_tracer().counters()["cache.write_errors"] == 1
        _CACHE.clear()
        _run_scale(tilelang.compile(_scale_func(7.5)), 7.5)  # …but rebuilds

    def test_torn_write_fault_caught_by_checksum(self):
        with inject("cache.disk.write", kind="corrupt"):
            k = tilelang.compile(_scale_func(8.5))
        _run_scale(k, 8.5)                # in-memory kernel unaffected
        _CACHE.clear()
        _run_scale(tilelang.compile(_scale_func(8.5)), 8.5)
        assert len(_quarantined()) == 1   # torn entry detected, not reused

    def test_read_fault_is_miss_not_quarantine(self):
        tilelang.compile(_scale_func(9.5))
        _CACHE.clear()
        with inject("cache.disk.read", kind="oserror"):
            _run_scale(tilelang.compile(_scale_func(9.5)), 9.5)
        assert _quarantined() == []
        assert get_tracer().counters()["cache.read_errors"] == 1

    def test_clear_disk_purges_everything(self):
        tilelang.compile(_scale_func(10.5))
        (entry,) = _disk_entries()
        (entry / KERNEL_SOURCE_FILE).write_text("bad")
        _CACHE.clear()
        tilelang.compile(_scale_func(10.5))  # creates a quarantine too
        assert _disk_entries() and _quarantined()
        _CACHE.clear(disk=True)
        assert list(env.cache_dir().iterdir()) == []

    def test_key_unchanged_by_resilience_metadata(self):
        f = _scale_func(11.5)
        script = f.func.script()
        assert KernelCache.key_for(script, "cpu", None, {}) == \
            KernelCache.key_for(script, "cpu", None, {})


# ---------------------------------------------------------------------------
# hardened autotuner
# ---------------------------------------------------------------------------

def _copy_factory(calls):
    @tilelang.jit
    def factory(M, N, block_M=32):
        calls.append(block_M)

        @T.prim_func
        def k(A: T.Tensor((M, N), "float32"),
              B: T.Tensor((M, N), "float32")):
            with T.Kernel(T.ceildiv(M, block_M)) as bx:
                s = T.alloc_shared((block_M, N), "float32")
                T.copy(A[bx * block_M, 0], s)
                T.copy(s, B[bx * block_M, 0])
        return k
    return factory


class TestAutotunerResilience:
    def test_transient_faults_still_find_winner(self):
        calls = []
        factory = _copy_factory(calls)
        from tilelang_mesh_tpu.autotuner import AutoTuner
        with inject("autotune.trial", p=0.5, seed=3):
            res = AutoTuner(factory, [{"block_M": 32}, {"block_M": 64}],
                            warmup=1, rep=2, cache_results=False
                            ).run(128, 128)
        assert res.latency_ms > 0
        assert res.config in ({"block_M": 32}, {"block_M": 64})

    def test_journal_resumes_interrupted_sweep(self):
        calls = []
        factory = _copy_factory(calls)
        from tilelang_mesh_tpu.autotuner import AutoTuner, _config_key
        configs = [{"block_M": 32}, {"block_M": 64}]
        tuner = AutoTuner(factory, configs, warmup=1, rep=2,
                          cache_results=True)
        key = tuner._disk_key((128, 128), {}, configs)
        journal = env.autotune_dir() / f"{key}.journal.jsonl"
        # an interrupted sweep already measured block_M=32 at 0.001 ms
        # (stamped with THIS build's schema/codegen — unstamped or
        # mismatched records are deliberately skipped as stale, see
        # test_cost_model.py::test_journal_skips_stale_codegen)
        from tilelang_mesh_tpu.autotuner import _JOURNAL_SCHEMA
        from tilelang_mesh_tpu.cache.kernel_cache import CODEGEN_VERSION
        journal.write_text(json.dumps(
            {"config_key": _config_key(configs[0]), "status": "ok",
             "latency_ms": 0.001, "schema": _JOURNAL_SCHEMA,
             "codegen_version": CODEGEN_VERSION}) + "\n")
        res = tuner.run(128, 128)
        # the journaled config won without re-benchmarking; its kernel is
        # built once at the end (so 32 appears once, not warmup+rep times)
        assert res.config == {"block_M": 32}
        assert res.latency_ms == 0.001
        assert res.kernel is not None
        resumed = [r for r in res.all_results if r.get("resumed")]
        assert len(resumed) == 1
        # completed sweep: result durable, journal retired
        assert not journal.exists()
        assert (env.autotune_dir() / f"{key}.json").exists()

    def test_journal_skips_deterministic_failures(self):
        calls = []
        factory = _copy_factory(calls)
        from tilelang_mesh_tpu.autotuner import AutoTuner, _config_key
        configs = [{"block_M": 32}, {"block_M": 64}]
        tuner = AutoTuner(factory, configs, warmup=1, rep=2,
                          cache_results=True)
        key = tuner._disk_key((128, 128), {}, configs)
        journal = env.autotune_dir() / f"{key}.journal.jsonl"
        from tilelang_mesh_tpu.autotuner import _JOURNAL_SCHEMA
        from tilelang_mesh_tpu.cache.kernel_cache import CODEGEN_VERSION
        journal.write_text(json.dumps(
            {"config_key": _config_key(configs[0]), "status": "failed",
             "kind": "deterministic", "error": "TypeError: broken",
             "schema": _JOURNAL_SCHEMA,
             "codegen_version": CODEGEN_VERSION}) + "\n")
        res = tuner.run(128, 128)
        assert res.config == {"block_M": 64}
        assert 32 not in calls             # known-bad config never re-paid
        skipped = [r for r in res.all_results if r.get("skipped")]
        assert len(skipped) == 1

    def test_sweep_journals_outcomes_as_it_goes(self, monkeypatch):
        calls = []
        factory = _copy_factory(calls)
        from tilelang_mesh_tpu.autotuner import AutoTuner, _append_journal
        recorded = []
        monkeypatch.setattr(
            "tilelang_mesh_tpu.autotuner._append_journal",
            lambda path, rec: recorded.append((path, rec)) or
            _append_journal(path, rec))
        AutoTuner(factory, [{"block_M": 32}, {"block_M": 64}],
                  warmup=1, rep=2, cache_results=True).run(128, 128)
        assert len(recorded) == 2
        assert all(r["status"] == "ok" for _, r in recorded)

    def test_all_failing_still_raises(self):
        from tilelang_mesh_tpu.autotuner import AutoTuner

        def factory(M, N, block_M=32):
            raise TypeError("factory is broken")
        with pytest.raises(RuntimeError, match="every candidate"):
            AutoTuner(factory, [{"block_M": 32}], warmup=1, rep=1,
                      cache_results=False).run(128, 128)

    def test_breaker_fast_skips_systematic_failures(self, monkeypatch):
        """Once `threshold` consecutive trials die with one identical
        deterministic signature, the remaining configs fast-fail without
        running (no more timeout budget burned on a systemic bug)."""
        monkeypatch.setenv("TL_TPU_BREAKER_THRESHOLD", "2")
        from tilelang_mesh_tpu.autotuner import AutoTuner
        calls = []

        def factory(M, N, block_M=32):
            calls.append(block_M)
            raise TypeError("systemic codegen bug")
        configs = [{"block_M": b} for b in (16, 32, 64, 128, 256)]
        with pytest.raises(RuntimeError, match="every candidate"):
            AutoTuner(factory, configs, warmup=1, rep=1,
                      cache_results=False).run(128, 128)
        assert len(calls) == 2     # trials 3-5 never ran
        assert get_tracer().counters()["autotune.breaker_skips"] == 3

    def test_success_resets_failure_streak(self, monkeypatch):
        """Distinct failure signatures / interleaved successes must not
        trip the fast-skip: only a uniform consecutive streak does."""
        monkeypatch.setenv("TL_TPU_BREAKER_THRESHOLD", "2")
        calls = []
        factory = _copy_factory(calls)

        def flaky_factory(M, N, block_M=32):
            if block_M in (16, 256):   # distinct errors per config
                raise TypeError(f"bad tile {block_M}")
            return factory(M, N, block_M=block_M)
        from tilelang_mesh_tpu.autotuner import AutoTuner
        res = AutoTuner(flaky_factory,
                        [{"block_M": 16}, {"block_M": 32},
                         {"block_M": 256}, {"block_M": 64}],
                        warmup=1, rep=1, cache_results=False).run(128, 128)
        assert res.config in ({"block_M": 32}, {"block_M": 64})
        assert set(calls) == {32, 64}  # both good configs actually ran

    def test_timeout_worker_tracked_and_uniquely_named(self):
        from tilelang_mesh_tpu.autotuner import (abandoned_worker_count,
                                                 run_with_timeout)
        before = get_tracer().counters().get("autotune.abandoned_threads", 0)
        with pytest.raises(concurrent.futures.TimeoutError) as ei:
            run_with_timeout(time.sleep, 0.2, 2.0)
        assert "tl-autotune-timeout-" in str(ei.value)
        assert abandoned_worker_count() >= 1
        assert get_tracer().counters()["autotune.abandoned_threads"] == \
            before + 1
        with pytest.raises(concurrent.futures.TimeoutError) as ei2:
            run_with_timeout(time.sleep, 0.2, 2.0)
        assert str(ei.value) != str(ei2.value)   # unique worker names


# ---------------------------------------------------------------------------
# graceful degradation (interpreter fallback)
# ---------------------------------------------------------------------------

class TestFallback:
    def test_compile_fault_falls_back_to_interpreter(self, monkeypatch):
        monkeypatch.setenv("TL_TPU_TRACE", "1")
        get_tracer().reset()
        with inject("jit.compile", times=1):
            k = tilelang.compile(_scale_func(12.5))
        assert k._degraded
        _run_scale(k, 12.5)                # numerically correct output
        evs = [e for e in get_tracer().events() if e["name"] == "degraded"]
        assert len(evs) == 1
        assert evs[0]["attrs"]["kernel"] == "scale"
        assert get_tracer().counters()["resilience.degraded"] == 1

    def test_fallback_none_fails_fast(self, monkeypatch):
        monkeypatch.setenv("TL_TPU_FALLBACK", "none")
        with inject("jit.compile", times=1):
            with pytest.raises(InjectedFault):
                tilelang.compile(_scale_func(13.5))

    def test_lower_transient_fault_retried_by_cached(self):
        # one transient lowering fault: the compile path retries and the
        # kernel still builds + caches
        with inject("lower.plan", times=1):
            k = tilelang.compile(_scale_func(14.5))
        _run_scale(k, 14.5)
        assert get_tracer().counters().get(
            "resilience.retry{kind=transient,site=lower}", 0) == 1

    def test_lower_deterministic_fault_propagates(self):
        with inject("lower.plan", kind="deterministic", times=1):
            with pytest.raises(DeterministicError):
                tilelang.compile(_scale_func(15.5))

    def test_degrade_only_for_compile_shaped_errors(self):
        """User errors (builtin exceptions from user code) must propagate,
        not silently pin the kernel to the interpreter."""
        from tilelang_mesh_tpu.jit.kernel import _compile_shaped
        assert _compile_shaped(InjectedFault("chaos"))
        assert _compile_shaped(NotImplementedError("mosaic op"))
        assert not _compile_shaped(ValueError("bad data"))
        assert not _compile_shaped(TypeError("bad operand"))

    def test_cache_timeout_fault_nonfatal(self):
        """kind=timeout / kind=deterministic write faults must also
        degrade to an uncached compile, not abort it."""
        with inject("cache.disk.write", kind="timeout"):
            _run_scale(tilelang.compile(_scale_func(18.5)), 18.5)
        _CACHE.clear()
        with inject("cache.disk.read", kind="deterministic"):
            _run_scale(tilelang.compile(_scale_func(18.5)), 18.5)
        assert get_tracer().counters()["cache.write_errors"] == 1
        assert get_tracer().counters()["cache.read_errors"] == 1


# ---------------------------------------------------------------------------
# mesh-config validation (satellite)
# ---------------------------------------------------------------------------

class TestMeshConfigValidation:
    def test_set_device_mesh_config_rejects_bad_dims(self):
        from tilelang_mesh_tpu.parallel.device_mesh import (
            get_device_mesh_config, set_device_mesh_config)
        keep = get_device_mesh_config()
        try:
            for bad in ((0, 4), (4, 0), (-1, 2), (2, -3)):
                with pytest.raises(ValueError, match=str(bad)):
                    set_device_mesh_config(*bad)
            assert get_device_mesh_config() == keep   # unchanged on error
        finally:
            set_device_mesh_config(*keep)

    def test_mesh_config_scope_rejects_bad_dims(self):
        from tilelang_mesh_tpu.parallel.device_mesh import (
            get_device_mesh_config, mesh_config)
        with pytest.raises(ValueError, match=r"\(0, 2\)"):
            with mesh_config(0, 2):
                pass
        with mesh_config(2, 2):
            assert get_device_mesh_config() == (2, 2)

    def test_valid_dims_accepted(self):
        from tilelang_mesh_tpu.parallel.device_mesh import (
            get_device_mesh_config, set_device_mesh_config)
        keep = get_device_mesh_config()
        try:
            set_device_mesh_config(1, 1)
            assert get_device_mesh_config() == (1, 1)
        finally:
            set_device_mesh_config(*keep)


# ---------------------------------------------------------------------------
# analyzer --faults (satellite)
# ---------------------------------------------------------------------------

class TestAnalyzerFaults:
    def test_faults_report_from_trace(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TL_TPU_TRACE", "1")
        get_tracer().reset()
        with inject("jit.compile", times=1):
            k = tilelang.compile(_scale_func(16.5))
        _run_scale(k, 16.5)
        with inject("lower.plan", times=1):
            tilelang.compile(_scale_func(17.5))
        from tilelang_mesh_tpu.observability import write_jsonl
        trace_f = tmp_path / "trace.jsonl"
        write_jsonl(trace_f)
        from tilelang_mesh_tpu.tools.analyzer import (format_faults_report,
                                                      summarize_faults)
        from tilelang_mesh_tpu.observability import read_jsonl
        s = summarize_faults(read_jsonl(trace_f))
        assert s["injected"]["jit.compile"] == 1
        assert s["injected"]["lower.plan"] == 1
        assert s["retries"].get("lower", 0) == 1
        assert s["degraded"] == {"scale": 1}
        report = format_faults_report(read_jsonl(trace_f))
        assert "jit.compile" in report and "degraded" in report

    def test_cli_faults_flag(self, tmp_path, capsys):
        trace_f = tmp_path / "t.jsonl"
        trace_f.write_text(json.dumps(
            {"type": "event", "name": "fault.injected",
             "attrs": {"site": "autotune.trial", "kind": "transient"}}) +
            "\n")
        from tilelang_mesh_tpu.tools.analyzer import main
        assert main(["--faults", str(trace_f)]) == 0
        out = capsys.readouterr().out
        assert "autotune.trial" in out

    def test_cli_requires_an_input(self):
        from tilelang_mesh_tpu.tools.analyzer import main
        with pytest.raises(SystemExit):
            main([])


# ---------------------------------------------------------------------------
# end-to-end seeded chaos sweep (the acceptance scenario)
# ---------------------------------------------------------------------------

@pytest.mark.chaos
class TestEndToEndChaos:
    def test_armed_pipeline_survives_and_is_observable(self, monkeypatch):
        """TL_TPU_FAULTS arms disk-write (torn), trial, and compile
        faults at p=0.3 (seeded); the jit + autotune run must complete
        with numerically correct results, torn entries must land in
        .quarantine/, and the trace must show the matching fault/retry/
        degraded events."""
        monkeypatch.setenv("TL_TPU_TRACE", "1")
        monkeypatch.setenv(
            "TL_TPU_FAULTS",
            "cache.disk.write:p=0.3:seed=3:kind=corrupt;"
            "autotune.trial:p=0.3:seed=12;"
            "jit.compile:p=0.3:seed=13")
        get_tracer().reset()
        # jit + cache path: compile several kernels, then reload each
        # from disk in a fresh memory tier
        mults = [21.0, 22.0, 23.0, 24.0, 25.0]
        for m in mults:
            _run_scale(tilelang.compile(_scale_func(m)), m)
        _CACHE.clear()
        for m in mults:
            _run_scale(tilelang.compile(_scale_func(m)), m)
        # autotune path
        calls = []
        factory = _copy_factory(calls)
        from tilelang_mesh_tpu.autotuner import AutoTuner
        res = AutoTuner(factory, [{"block_M": 32}, {"block_M": 64}],
                        warmup=1, rep=2, cache_results=False).run(128, 128)
        assert res.latency_ms > 0
        # every injected fault is observable, and recovery matched it
        counters = get_tracer().counters()
        injected = sum(v for k, v in counters.items()
                       if k.startswith("fault.injected"))
        assert injected > 0, "p=0.3 over this many visits must fire"
        names = {e["name"] for e in get_tracer().events()}
        assert "fault.injected" in names
        # torn writes were quarantined on reload, never silently reused
        if any("site=cache.disk.write" in k for k in counters):
            assert counters.get("cache.quarantined", 0) >= 1
            assert len(_quarantined()) >= 1
        if any("site=jit.compile" in k for k in counters):
            assert counters.get("resilience.degraded", 0) >= 1
            assert "degraded" in names
        if any("site=autotune.trial" in k for k in counters):
            assert "resilience.retry" in names


class TestOverheadWhenDisabled:
    def test_maybe_fail_is_noop_without_arming(self):
        """With TL_TPU_FAULTS unset the hook must be branch-cheap: no
        parsing, no RNG, no tracer traffic."""
        from tilelang_mesh_tpu.resilience import faults
        assert faults.active_specs() == []
        t0 = time.perf_counter()
        for _ in range(20_000):
            maybe_fail("cache.disk.read")
        dt = time.perf_counter() - t0
        assert dt < 0.5                    # ~μs/call budget, generous CI bar
        assert "fault.injected" not in " ".join(get_tracer().counters())

    def test_cached_kernel_call_unchanged(self):
        """The resilience hooks sit on compile paths only: a cached
        kernel dispatch records nothing new."""
        k = tilelang.compile(_scale_func(31.0))
        _run_scale(k, 31.0)
        get_tracer().reset()
        _run_scale(k, 31.0)
        assert get_tracer().counters() == {}
