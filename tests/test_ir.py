"""Tile-IR unit tests: expression folding, affine analysis, buffers,
regions (SURVEY §4 style 3: pure-python property tests)."""

import pytest

from tilelang_mesh_tpu.ir import (Buffer, IntImm, Var, as_int, ceildiv,
                                  convert, linearize, to_region)
from tilelang_mesh_tpu.ir.expr import affine_decompose, rebuild_affine


def test_constant_folding():
    a = convert(3) + convert(4)
    assert as_int(a) == 7
    assert as_int(convert(10) * 5 - 1) == 49
    assert as_int(ceildiv(100, 32)) == 4
    assert as_int(ceildiv(96, 32)) == 3


def test_algebraic_identities():
    i = Var("i")
    assert (i + 0) is i
    assert (i * 1) is i
    assert as_int(i * 0) == 0
    assert (i - 0) is i


def test_linearize_affine():
    i, j = Var("i"), Var("j")
    e = i * 128 + j * 32 + 64
    coeffs, const = linearize(e, [i, j])
    assert coeffs[i] == 128 and coeffs[j] == 32 and const == 64


def test_linearize_rejects_nonlinear():
    i, j = Var("i"), Var("j")
    assert linearize(i * j, [i, j]) is None
    # mentions a var outside wrt
    assert linearize(i + j, [i]) is None


def test_affine_decompose_cancellation():
    i, g = Var("i"), Var("g")
    e = (g * 128 + i) - g * 128
    coeffs, const = affine_decompose(e)
    assert const == 0
    assert len(coeffs) == 1
    (v, c), = coeffs.values()
    assert v is i and c == 1


def test_rebuild_affine_roundtrip():
    i, j = Var("i"), Var("j")
    e = i * 4 + j * 2 + 9
    coeffs, const = affine_decompose(e)
    r = rebuild_affine(coeffs, const)
    c2, k2 = affine_decompose(r)
    assert k2 == 9
    assert {v.name: c for _, (v, c) in c2.items()} == {"i": 4, "j": 2}


def test_buffer_region_sugar():
    A = Buffer("A", (256, 128), "float32")
    r = to_region(A[0:128, 32:64])
    assert r.static_shape() == (128, 32)
    assert as_int(r.base[1]) == 32
    # element-access base with extent hint
    i = Var("i")
    r2 = to_region(A[i * 64, 0], extent_hint=(64, 128))
    assert r2.static_shape() == (64, 128)


def test_buffer_rank_mismatch_hint():
    # 4-D tensor copied into a 2-D tile: hint right-aligns
    Q = Buffer("Q", (2, 4, 256, 64), "float32")
    r = to_region(Q[0, 1, 0, 0], extent_hint=(128, 64))
    assert r.static_shape() == (1, 1, 128, 64)


def test_symbolic_bool_raises():
    i = Var("i")
    with pytest.raises(TypeError):
        bool(i < 5)


def test_dtype_promotion():
    from tilelang_mesh_tpu.ir import promote_dtypes
    assert promote_dtypes("float32", "bfloat16") == "float32"
    assert promote_dtypes("int32", "float16") == "float16"
    assert promote_dtypes("int8", "int32") == "int32"
