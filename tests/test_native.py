"""Native core build + parity with the pure-Python implementations."""

import numpy as np
import pytest

from tilelang_mesh_tpu.layout import (Fragment, HierarchicalLayout, Layout,
                                      allgather_schedule, allreduce_schedule,
                                      broadcast_schedule,
                                      make_blockwise_zz_layout,
                                      schedule_hops)
from tilelang_mesh_tpu.layout import native, python_impl as py


def test_native_builds_and_loads():
    assert native.available(), \
        "native library failed to build (make -C src)"


def test_layout_offset_matches():
    strides = [128, 1]
    for idx in [(0, 0), (3, 17), (7, 127)]:
        assert native.layout_offset(strides, idx) == \
            py.layout_offset(strides, idx)


def test_layout_compose_parity():
    shape_a = [8, 16]
    strides_a = [1, 8]       # column-major A
    strides_b = [16, 1]      # row-major view over A-logical
    assert native.layout_compose(shape_a, strides_a, strides_b) == \
        py.layout_compose(shape_a, strides_a, strides_b)


def test_layout_inverse_parity_and_correctness():
    # a transpose layout over (4, 8): offset = c*4 + r
    shape, strides = [4, 8], [1, 4]
    ns, nst = native.layout_inverse(shape, strides)
    ps, pst = py.layout_inverse(shape, strides)
    assert ns == ps and nst == pst
    lay = Layout(shape, strides)
    inv = lay.inverse()
    assert inv.shape == (8, 4)  # stride-descending factorization
    # inverse of a bijection: decompose the offset in inv's mixed radix,
    # apply inv -> recovers the logical row-major flat index
    for r in range(4):
        for c in range(8):
            off = lay(r, c)
            oi = (off // inv.shape[1], off % inv.shape[1])
            assert inv(oi) == r * 8 + c


def test_vmem_bytes_padding():
    # bf16 (16,128) min tile: 100x100 pads to 112x128
    assert native.vmem_bytes(100, 100, 16) == 112 * 128 * 2
    assert native.vmem_bytes(100, 100, 16) == py.vmem_bytes(100, 100, 16)
    # f32 pads sublane to 8
    assert py.vmem_bytes(4, 128, 32) == 8 * 128 * 4
    assert native.vmem_bytes(4, 128, 32) == 8 * 128 * 4


@pytest.mark.parametrize("direction", [0, 1, 2])
def test_schedule_parity(direction):
    for rows, cols in [(2, 4), (4, 4), (1, 1), (3, 2)]:
        assert native.broadcast_schedule(rows, cols, (0, min(1, cols - 1)),
                                         direction) == \
            py.broadcast_schedule(rows, cols, (0, min(1, cols - 1)),
                                  direction)
        assert native.allgather_schedule(rows, cols, direction) == \
            py.allgather_schedule(rows, cols, direction)
        assert native.allreduce_schedule(rows, cols, direction) == \
            py.allreduce_schedule(rows, cols, direction)


def test_schedule_hops_parity():
    steps = py.allgather_schedule(4, 4, 2)
    assert native.schedule_hops(steps, 4, 4) == py.schedule_hops(steps, 4, 4)


def test_blockwise_zz_parity_and_shape():
    n = native.blockwise_zz_owners(4, 4)
    p = py.blockwise_zz_owners(4, 4)
    assert n == p
    # zig-zag: row 1 reversed
    assert p[4:8] == [7, 6, 5, 4]
    assert make_blockwise_zz_layout(2, 2) == [0, 1, 3, 2]


def test_broadcast_all_is_v_then_h_rows():
    """Golden: 2-D broadcast = vertical down source column, then one
    horizontal per row (matches the reference's comm.cc decomposition)."""
    steps = broadcast_schedule(2, 4, (0, 1), 2)
    assert steps == [(0, 1, 1, 0), (0, 1, 0, 0), (1, 1, 0, 0)]


def test_allgather_all_two_phase():
    steps = allgather_schedule(2, 2, 2)
    h = [s for s in steps if s[2] == 0]
    v = [s for s in steps if s[2] == 1]
    assert len(h) == 4 and len(v) == 4
    assert steps[:4] == h  # horizontal phase first


def test_hierarchical_layout_offsets():
    # logical (8, 4) where dim0 factors into (2, 4): offset uses custom
    # strides per hierarchical dim
    hl = HierarchicalLayout(dims=(2, 4, 4), strides=(16, 4, 1),
                            groups=((0, 2), (2, 3)))
    assert hl.logical_shape() == (8, 4)
    assert hl.offset((0, 0)) == 0
    assert hl.offset((5, 2)) == 1 * 16 + 1 * 4 + 2  # 5 = (1, 1) in (2,4)


def test_fragment_cell_and_footprint():
    f = Fragment((100, 100), dtype_bits=16)
    assert f.vmem_bytes() == 112 * 128 * 2
    assert f.cell(0, 0) == (0, 0)
    assert f.cell(17, 129 % 100) == (17 % 16, 29 % 128)
