"""Native core build + parity with the pure-Python implementations."""

import numpy as np
import pytest

from tilelang_mesh_tpu.layout import (Fragment, HierarchicalLayout, Layout,
                                      allgather_schedule, allreduce_schedule,
                                      broadcast_schedule,
                                      make_blockwise_zz_layout,
                                      schedule_hops)
from tilelang_mesh_tpu.layout import native, python_impl as py


def test_native_builds_and_loads():
    assert native.available(), \
        "native library failed to build (make -C src)"


def test_layout_offset_matches():
    strides = [128, 1]
    for idx in [(0, 0), (3, 17), (7, 127)]:
        assert native.layout_offset(strides, idx) == \
            py.layout_offset(strides, idx)


def test_layout_compose_parity():
    shape_a = [8, 16]
    strides_a = [1, 8]       # column-major A
    strides_b = [16, 1]      # row-major view over A-logical
    assert native.layout_compose(shape_a, strides_a, strides_b) == \
        py.layout_compose(shape_a, strides_a, strides_b)


def test_layout_inverse_parity_and_correctness():
    # a transpose layout over (4, 8): offset = c*4 + r
    shape, strides = [4, 8], [1, 4]
    ns, nst = native.layout_inverse(shape, strides)
    ps, pst = py.layout_inverse(shape, strides)
    assert ns == ps and nst == pst
    lay = Layout(shape, strides)
    inv = lay.inverse()
    assert inv.shape == (8, 4)  # stride-descending factorization
    # inverse of a bijection: decompose the offset in inv's mixed radix,
    # apply inv -> recovers the logical row-major flat index
    for r in range(4):
        for c in range(8):
            off = lay(r, c)
            oi = (off // inv.shape[1], off % inv.shape[1])
            assert inv(oi) == r * 8 + c


def test_vmem_bytes_padding():
    # bf16 (16,128) min tile: 100x100 pads to 112x128
    assert native.vmem_bytes(100, 100, 16) == 112 * 128 * 2
    assert native.vmem_bytes(100, 100, 16) == py.vmem_bytes(100, 100, 16)
    # f32 pads sublane to 8
    assert py.vmem_bytes(4, 128, 32) == 8 * 128 * 4
    assert native.vmem_bytes(4, 128, 32) == 8 * 128 * 4


@pytest.mark.parametrize("direction", [0, 1, 2])
def test_schedule_parity(direction):
    for rows, cols in [(2, 4), (4, 4), (1, 1), (3, 2)]:
        assert native.broadcast_schedule(rows, cols, (0, min(1, cols - 1)),
                                         direction) == \
            py.broadcast_schedule(rows, cols, (0, min(1, cols - 1)),
                                  direction)
        assert native.allgather_schedule(rows, cols, direction) == \
            py.allgather_schedule(rows, cols, direction)
        assert native.allreduce_schedule(rows, cols, direction) == \
            py.allreduce_schedule(rows, cols, direction)


def test_schedule_hops_parity():
    steps = py.allgather_schedule(4, 4, 2)
    assert native.schedule_hops(steps, 4, 4) == py.schedule_hops(steps, 4, 4)


def test_blockwise_zz_parity_and_shape():
    n = native.blockwise_zz_owners(4, 4)
    p = py.blockwise_zz_owners(4, 4)
    assert n == p
    # zig-zag: row 1 reversed
    assert p[4:8] == [7, 6, 5, 4]
    assert make_blockwise_zz_layout(2, 2) == [0, 1, 3, 2]


def test_broadcast_all_is_v_then_h_rows():
    """Golden: 2-D broadcast = vertical down source column, then one
    horizontal per row (matches the reference's comm.cc decomposition)."""
    steps = broadcast_schedule(2, 4, (0, 1), 2)
    assert steps == [(0, 1, 1, 0), (0, 1, 0, 0), (1, 1, 0, 0)]


def test_allgather_all_two_phase():
    steps = allgather_schedule(2, 2, 2)
    h = [s for s in steps if s[2] == 0]
    v = [s for s in steps if s[2] == 1]
    assert len(h) == 4 and len(v) == 4
    assert steps[:4] == h  # horizontal phase first


def test_hierarchical_layout_offsets():
    # logical (8, 4) where dim0 factors into (2, 4): offset uses custom
    # strides per hierarchical dim
    hl = HierarchicalLayout(dims=(2, 4, 4), strides=(16, 4, 1),
                            groups=((0, 2), (2, 3)))
    assert hl.logical_shape() == (8, 4)
    assert hl.offset((0, 0)) == 0
    assert hl.offset((5, 2)) == 1 * 16 + 1 * 4 + 2  # 5 = (1, 1) in (2,4)


def test_fragment_cell_and_footprint():
    f = Fragment((100, 100), dtype_bits=16)
    assert f.vmem_bytes() == 112 * 128 * 2
    assert f.cell(0, 0) == (0, 0)
    assert f.cell(17, 129 % 100) == (17 % 16, 29 % 128)


def test_vmem_pack_parity_and_reuse():
    from tilelang_mesh_tpu.layout import native as lnat
    from tilelang_mesh_tpu.layout import python_impl as lpy
    rng = np.random.default_rng(0)
    for _ in range(20):
        n = int(rng.integers(1, 10))
        sizes = [int(rng.integers(1, 1 << 16)) for _ in range(n)]
        first = [int(rng.integers(0, 20)) for _ in range(n)]
        last = [f + int(rng.integers(0, 20)) for f in first]
        py = lpy.vmem_pack(sizes, first, last)
        assert py is not None
        arena_py, off_py = py
        if lnat.available():
            nat = lnat.vmem_pack(sizes, first, last)
            assert nat == (arena_py, off_py)
        # validity: live-overlapping buffers must not address-overlap
        align = 512
        for i in range(n):
            for j in range(i + 1, n):
                live = not (last[j] < first[i] or last[i] < first[j])
                szi = -(-sizes[i] // align) * align
                szj = -(-sizes[j] // align) * align
                addr = (off_py[i] < off_py[j] + szj and
                        off_py[j] < off_py[i] + szi)
                assert not (live and addr), (sizes, first, last, off_py)
    # disjoint lifetimes must actually share memory
    arena, _ = lpy.vmem_pack([4096, 4096], [0, 5], [4, 9])
    assert arena == 4096


def test_streamk_partition_parity():
    from tilelang_mesh_tpu.layout import native as lnat
    from tilelang_mesh_tpu.layout import python_impl as lpy
    for nt, ki, np_ in ((3, 4, 2), (7, 5, 3), (1, 1, 4), (16, 8, 5)):
        py = lpy.streamk_partition(nt, ki, np_)
        # covers the whole space exactly once
        covered = sorted((t, k0 + d) for t, k0, kl in py for d in range(kl))
        assert covered == [(t, k) for t in range(nt) for k in range(ki)]
        if lnat.available():
            assert [tuple(s) for s in
                    lnat.streamk_partition(nt, ki, np_)] == py


def test_affine_linearize_native_parity():
    from tilelang_mesh_tpu.ir import Var, linearize
    from tilelang_mesh_tpu.layout import native as lnat
    if not lnat.available():
        return
    x, y = Var("x"), Var("y")
    cases = [
        (x * 4 + y + 3, {x: 4, y: 1}, 3),
        ((x * 8 + y * 4) // 4, {x: 2, y: 1}, 0),
        (x * 2 + x * 3, {x: 5}, 0),
        ((x + 1) * 6 - y * 6, {x: 6, y: -6}, 6),
    ]
    for expr, coeffs, const in cases:
        r = linearize(expr, [x, y])
        assert r is not None
        got_c, got_k = r
        assert {v: c for v, c in got_c.items()} == coeffs and got_k == const
    # non-affine -> None through both paths
    assert linearize(x * y, [x, y]) is None
    assert linearize((x * 3 + 1) // 2, [x, y]) is None


# ---------------------------------------------------------------------------
# expression grid evaluation (round-3: tl_expr_eval_grid)
# ---------------------------------------------------------------------------

def _rand_program(rng, n_axes):
    """Random valid node program over the eval opcode set."""
    ops, a, b = [], [], []
    n = rng.integers(3, 14)
    for i in range(n):
        if i < 2 or rng.random() < 0.3:
            if rng.random() < 0.5:
                ops.append(0)
                a.append(int(rng.integers(-7, 17)) or 3)
                b.append(0)
            else:
                ops.append(1)
                a.append(int(rng.integers(0, n_axes)))
                b.append(0)
        else:
            ops.append(int(rng.integers(2, 9)))
            a.append(int(rng.integers(0, i)))
            b.append(int(rng.integers(0, i)))
    return ops, a, b


def test_expr_eval_grid_native_python_parity():
    from tilelang_mesh_tpu.layout import native as lnat
    from tilelang_mesh_tpu.layout import python_impl as lpy
    if not lnat.available():
        pytest.skip("native lib not built")
    rng = np.random.default_rng(0)
    checked = 0
    for _ in range(60):
        extents = tuple(int(rng.integers(1, 5)) for _ in range(2))
        ops, a, b = _rand_program(rng, len(extents))
        gn = lnat.expr_eval_grid(ops, a, b, extents)
        gp = lpy.expr_eval_grid(ops, a, b, extents)
        assert (gn is None) == (gp is None), (ops, a, b)
        if gn is not None:
            assert gn == gp, (ops, a, b)
            checked += 1
    assert checked > 20  # the generator must produce mostly-valid programs


def test_expr_eval_grid_matches_ir_eval():
    """The encoded program must agree with the tree interpreter the plan
    falls back to (_eval_expr) for a modular map."""
    from tilelang_mesh_tpu.ir import Var
    from tilelang_mesh_tpu.ir.expr import encode_expr
    from tilelang_mesh_tpu.layout import python_impl as lpy
    from tilelang_mesh_tpu.transform.plan import _eval_expr
    bx, by = Var("bx", "int32"), Var("by", "int32")
    e = ((bx + by * 3) % 4) * 2 + (bx // 2)
    enc = encode_expr(e, {id(bx): 0, id(by): 1})
    assert enc is not None
    vals = lpy.expr_eval_grid(*enc, (4, 3))
    import itertools
    want = [_eval_expr(e, {id(bx): x, id(by): y})
            for x, y in itertools.product(range(4), range(3))]
    assert vals == want


def test_expr_eval_grid_floor_semantics():
    """Negative intermediates must use python floor division, not C
    truncation."""
    from tilelang_mesh_tpu.layout import native as lnat
    from tilelang_mesh_tpu.layout import python_impl as lpy
    # (x0 - 3) // 2 over x0 in 0..5 -> [-2, -1, -1, 0, 0, 1]
    ops = [1, 0, 3, 0, 5]
    a = [0, 3, 0, 2, 2]
    b = [0, 0, 1, 0, 3]
    want = [(x - 3) // 2 for x in range(6)]
    assert lpy.expr_eval_grid(ops, a, b, (6,)) == want
    if lnat.available():
        assert lnat.expr_eval_grid(ops, a, b, (6,)) == want
