"""Mamba2 chunk-scan vs sequential SSM recurrence."""

import numpy as np

import jax.numpy as jnp

from tilelang_mesh_tpu.ops.mamba2 import (mamba2_chunk_scan,
                                          mamba2_reference)
from tilelang_mesh_tpu.utils.tensor import assert_allclose


def test_mamba2_chunk_scan_matches_recurrence():
    B, S, H, P, N = 1, 512, 2, 64, 64
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((B, S, H, P)) * 0.5, jnp.float32)
    dt = jnp.asarray(rng.uniform(0.01, 0.1, (B, S, H)), jnp.float32)
    A = jnp.asarray(-rng.uniform(0.5, 1.5, (H,)), jnp.float32)
    Bm = jnp.asarray(rng.standard_normal((B, S, N)) * 0.3, jnp.float32)
    Cm = jnp.asarray(rng.standard_normal((B, S, N)) * 0.3, jnp.float32)
    y = mamba2_chunk_scan(x, dt, A, Bm, Cm, chunk=128)
    ref = mamba2_reference(x, dt, A, Bm, Cm)
    assert y.shape == ref.shape == (B, S, H, P)
    assert_allclose(np.asarray(y), np.asarray(ref), rtol=2e-2, atol=2e-2)


def test_mamba2_multi_chunk_state_carry():
    """Cross-chunk state must carry: a single chunk vs two chunks of the
    same data differ unless the state path is correct."""
    B, S, H, P, N = 1, 256, 1, 32, 32
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((B, S, H, P)) * 0.5, jnp.float32)
    dt = jnp.asarray(rng.uniform(0.05, 0.2, (B, S, H)), jnp.float32)
    A = jnp.asarray([-1.0], jnp.float32)
    Bm = jnp.asarray(rng.standard_normal((B, S, N)) * 0.3, jnp.float32)
    Cm = jnp.asarray(rng.standard_normal((B, S, N)) * 0.3, jnp.float32)
    y_small_chunks = mamba2_chunk_scan(x, dt, A, Bm, Cm, chunk=64)
    ref = mamba2_reference(x, dt, A, Bm, Cm)
    assert_allclose(np.asarray(y_small_chunks), np.asarray(ref), rtol=2e-2,
                    atol=2e-2)


def test_mamba2_long_chunk_large_decay_no_overflow():
    """Strong decay over a long chunk: the factored exp(+|A| cumsum(dt))
    form overflows f32 (exp arg > 88); the pairwise segsum form must not."""
    B, S, H, P, N = 1, 256, 1, 32, 32
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((B, S, H, P)) * 0.5, jnp.float32)
    dt = jnp.asarray(rng.uniform(0.4, 0.6, (B, S, H)), jnp.float32)
    A = jnp.asarray([-1.0], jnp.float32)   # |A| * sum(dt) ~ 128 >> 88
    Bm = jnp.asarray(rng.standard_normal((B, S, N)) * 0.3, jnp.float32)
    Cm = jnp.asarray(rng.standard_normal((B, S, N)) * 0.3, jnp.float32)
    y = mamba2_chunk_scan(x, dt, A, Bm, Cm, chunk=256)
    ref = mamba2_reference(x, dt, A, Bm, Cm)
    assert np.isfinite(np.asarray(y)).all()
    assert_allclose(np.asarray(y), np.asarray(ref), rtol=2e-2, atol=2e-2)


def test_mamba2_xla_baseline_matches_recurrence():
    """The chunk-parallel XLA baseline (the benchmark's A/B counterpart,
    bench.py cfg_mamba2_chunk) must itself match the sequential
    recurrence — a wrong baseline makes the benchmark meaningless."""
    from tilelang_mesh_tpu.ops.mamba2 import mamba2_chunk_scan_xla
    B, S, H, P, N = 2, 512, 2, 64, 64
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((B, S, H, P)) * 0.5, jnp.float32)
    dt = jnp.asarray(rng.uniform(0.01, 0.1, (B, S, H)), jnp.float32)
    A = jnp.asarray(-rng.uniform(0.5, 1.5, (H,)), jnp.float32)
    Bm = jnp.asarray(rng.standard_normal((B, S, N)) * 0.3, jnp.float32)
    Cm = jnp.asarray(rng.standard_normal((B, S, N)) * 0.3, jnp.float32)
    y = mamba2_chunk_scan_xla(x, dt, A, Bm, Cm, chunk=128)
    ref = mamba2_reference(x, dt, A, Bm, Cm)
    assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-3, atol=1e-3)
    # chunk-size invariance of the baseline
    y64 = mamba2_chunk_scan_xla(x, dt, A, Bm, Cm, chunk=64)
    assert_allclose(np.asarray(y64), np.asarray(y), rtol=1e-4, atol=1e-4)
