"""Math-intrinsic parity: every T.* math op against its numpy reference
(the reference's testing/python/math + fastmath dirs). One kernel per op,
applied elementwise over a VPU tile; fastmath __exp/__log aliases map to
the same XLA ops on TPU (Mosaic owns transcendental lowering) and are
checked for numeric agreement rather than separate codegen.
"""

import numpy as np
import pytest

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T

M, N = 8, 128

_UNARY = [
    ("exp", np.exp, (-3.0, 3.0)),
    ("exp2", np.exp2, (-3.0, 3.0)),
    ("log", np.log, (0.1, 9.0)),
    ("log2", np.log2, (0.1, 9.0)),
    ("log10", np.log10, (0.1, 9.0)),
    ("log1p", np.log1p, (-0.5, 5.0)),
    ("sqrt", np.sqrt, (0.0, 9.0)),
    ("rsqrt", lambda x: 1.0 / np.sqrt(x), (0.1, 9.0)),
    ("sin", np.sin, (-3.0, 3.0)),
    ("cos", np.cos, (-3.0, 3.0)),
    ("tanh", np.tanh, (-3.0, 3.0)),
    ("sigmoid", lambda x: 1.0 / (1.0 + np.exp(-x)), (-4.0, 4.0)),
    ("erf", None, (-2.0, 2.0)),       # scipy-free: checked via math.erf
    ("floor", np.floor, (-4.0, 4.0)),
    ("ceil", np.ceil, (-4.0, 4.0)),
    ("abs", np.abs, (-4.0, 4.0)),
    ("__exp", np.exp, (-3.0, 3.0)),   # fastmath alias
    ("__log", np.log, (0.1, 9.0)),
]


def _apply_unary(op_name):
    op = getattr(T, op_name)

    @T.prim_func
    def k(A: T.Tensor((M, N), "float32"), O: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_shared((M, N), "float32")
            T.copy(A, s)
            for i, j in T.Parallel(M, N):
                s[i, j] = op(s[i, j])
            T.copy(s, O)
    return tilelang.compile(k)


@pytest.mark.parametrize("name,ref,rng_range",
                         _UNARY, ids=[u[0] for u in _UNARY])
def test_unary_intrinsic(name, ref, rng_range):
    lo, hi = rng_range
    rng = np.random.default_rng(hash(name) % 2 ** 31)
    a = (rng.random((M, N)) * (hi - lo) + lo).astype(np.float32)
    out = np.empty_like(a)
    _apply_unary(name)(a, out)
    if ref is None:
        import math
        ref_v = np.vectorize(math.erf)(a).astype(np.float32)
    else:
        ref_v = ref(a).astype(np.float32)
    np.testing.assert_allclose(out, ref_v, rtol=2e-5, atol=2e-5)


def test_binary_intrinsics():
    rng = np.random.default_rng(0)
    a = (rng.random((M, N)) * 4 + 0.5).astype(np.float32)
    b = (rng.random((M, N)) * 2 + 0.5).astype(np.float32)

    @T.prim_func
    def k(A: T.Tensor((M, N), "float32"), B: T.Tensor((M, N), "float32"),
          O: T.Tensor((4, M, N), "float32")):
        with T.Kernel(1) as bx:
            sa = T.alloc_shared((M, N), "float32")
            sb = T.alloc_shared((M, N), "float32")
            o = T.alloc_shared((4, M, N), "float32")
            T.copy(A, sa)
            T.copy(B, sb)
            for i, j in T.Parallel(M, N):
                o[0, i, j] = T.pow(sa[i, j], sb[i, j])
                o[1, i, j] = T.max(sa[i, j], sb[i, j])
                o[2, i, j] = T.min(sa[i, j], sb[i, j])
                o[3, i, j] = T.atan2(sa[i, j], sb[i, j])
            T.copy(o, O)

    out = np.empty((4, M, N), np.float32)
    tilelang.compile(k)(a, b, out)
    np.testing.assert_allclose(out[0], a ** b, rtol=1e-4)
    np.testing.assert_allclose(out[1], np.maximum(a, b), rtol=1e-6)
    np.testing.assert_allclose(out[2], np.minimum(a, b), rtol=1e-6)
    np.testing.assert_allclose(out[3], np.arctan2(a, b), rtol=1e-5)


def test_clamp_select_if_then_else():
    rng = np.random.default_rng(1)
    a = (rng.standard_normal((M, N)) * 3).astype(np.float32)

    @T.prim_func
    def k(A: T.Tensor((M, N), "float32"), O: T.Tensor((2, M, N), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_shared((M, N), "float32")
            o = T.alloc_shared((2, M, N), "float32")
            T.copy(A, s)
            for i, j in T.Parallel(M, N):
                o[0, i, j] = T.clamp(s[i, j], -1.0, 1.0)
                o[1, i, j] = T.if_then_else(s[i, j] > 0.0, s[i, j], 0.0)
            T.copy(o, O)

    out = np.empty((2, M, N), np.float32)
    tilelang.compile(k)(a, out)
    np.testing.assert_allclose(out[0], np.clip(a, -1, 1), rtol=1e-6)
    np.testing.assert_allclose(out[1], np.maximum(a, 0), rtol=1e-6)


def test_integer_bit_intrinsics():
    rng = np.random.default_rng(2)
    a = rng.integers(0, 255, (M, N), dtype=np.int32)

    @T.prim_func
    def k(A: T.Tensor((M, N), "int32"), O: T.Tensor((4, M, N), "int32")):
        with T.Kernel(1) as bx:
            s = T.alloc_shared((M, N), "int32")
            o = T.alloc_shared((4, M, N), "int32")
            T.copy(A, s)
            for i, j in T.Parallel(M, N):
                o[0, i, j] = T.shift_left(s[i, j], 2)
                o[1, i, j] = T.shift_right(s[i, j], 3)
                o[2, i, j] = T.bitwise_and(s[i, j], 0xF)
                o[3, i, j] = T.bitwise_xor(s[i, j], 0xAA)
            T.copy(o, O)

    out = np.empty((4, M, N), np.int32)
    tilelang.compile(k)(a, out)
    np.testing.assert_array_equal(out[0], a << 2)
    np.testing.assert_array_equal(out[1], a >> 3)
    np.testing.assert_array_equal(out[2], a & 0xF)
    np.testing.assert_array_equal(out[3], a ^ 0xAA)
