"""Host dispatch fast path (jit/dispatch.py; docs/host_dispatch.md).

Covers the PR 7 tentpole: warm/cold result equivalence through the
precompiled dispatch plan, buffer donation semantics
(``TL_TPU_DONATE``), torch/numpy dlpack round-trips in ``to_jax`` /
``copy_back``, fingerprint-vs-slow-path error parity, the
``dispatch.overhead`` histogram split, and the fast path's interplay
with the PR 6 device-loss failover machinery.
"""

import numpy as np
import pytest

import tilelang_mesh_tpu as tilelang
import tilelang_mesh_tpu.language as T
from tilelang_mesh_tpu.observability import histogram as _hist
from tilelang_mesh_tpu.observability import metrics_summary
from tilelang_mesh_tpu.observability.runtime import HIST_NAME, OVERHEAD_HIST
from tilelang_mesh_tpu.resilience import inject
from tilelang_mesh_tpu.utils.tensor import copy_back, to_jax

M, N = 64, 128


@pytest.fixture(autouse=True)
def _fresh_state(monkeypatch):
    """Dispatch-path state is process-global (kernel cache, backend
    health, histograms): every test starts clean and leaves no armed
    knobs behind."""
    from tilelang_mesh_tpu.codegen.backends import registry
    import tilelang_mesh_tpu.observability as obs
    for var in ("TL_TPU_FAST_DISPATCH", "TL_TPU_DONATE",
                "TL_TPU_RUNTIME_METRICS", "TL_TPU_RUNTIME_SAMPLE"):
        monkeypatch.delenv(var, raising=False)
    registry().reset()
    tilelang.clear_cache()
    obs.reset()
    yield
    registry().reset()
    tilelang.clear_cache()
    obs.reset()


def _scale_func(mult):
    @T.prim_func
    def scale(A: T.Tensor((M, N), "float32"),
              B: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_shared((M, N), "float32")
            T.copy(A, s)
            for i, j in T.Parallel(M, N):
                s[i, j] = s[i, j] * mult
            T.copy(s, B)
    return scale


def _bump_func():
    """An in-place (inout role) kernel: reads AND writes A."""
    @T.prim_func
    def bump(A: T.Tensor((M, N), "float32")):
        with T.Kernel(1) as bx:
            s = T.alloc_shared((M, N), "float32")
            T.copy(A, s)
            for i, j in T.Parallel(M, N):
                s[i, j] = s[i, j] + 1.0
            T.copy(s, A)
    return bump


def _data():
    rng = np.random.default_rng(3)
    return rng.standard_normal((M, N)).astype(np.float32)


# ---------------------------------------------------------------------------
# plan structure + warm/cold equivalence
# ---------------------------------------------------------------------------

class TestDispatchPlan:
    def test_plan_precomputed(self):
        import jax.numpy as jnp
        k = tilelang.compile(_scale_func(2.5))
        plan = k._plan
        assert plan.n_in == 1
        assert plan.expected_fp == (((M, N), jnp.dtype("float32")),)
        assert plan.donate_argnums == ()   # no inout params
        assert plan.fast_on and plan.donate_on is False

    def test_cold_then_warm_equivalence(self):
        import jax.numpy as jnp
        k = tilelang.compile(_scale_func(2.5))
        a = _data()
        cold = np.asarray(k(a))
        warm = np.asarray(k(a))
        warm_jax = np.asarray(k(jnp.asarray(a)))
        np.testing.assert_allclose(cold, a * 2.5, rtol=1e-6)
        np.testing.assert_array_equal(cold, warm)
        np.testing.assert_array_equal(cold, warm_jax)

    def test_fast_matches_legacy(self, monkeypatch):
        k = tilelang.compile(_scale_func(3.0))
        a = _data()
        fast = np.asarray(k(a))
        monkeypatch.setenv("TL_TPU_FAST_DISPATCH", "0")
        legacy = np.asarray(k(a))
        np.testing.assert_array_equal(fast, legacy)
        monkeypatch.delenv("TL_TPU_FAST_DISPATCH")
        np.testing.assert_array_equal(np.asarray(k(a)), fast)

    def test_shape_mismatch_same_valueerror(self):
        k = tilelang.compile(_scale_func(2.5))
        k(_data())   # warm the plan first
        with pytest.raises(ValueError,
                           match=r"param A expects shape \(64, 128\)"):
            k(np.zeros((8, 8), np.float32))

    def test_dtype_mismatch_same_valueerror(self):
        k = tilelang.compile(_scale_func(2.5))
        k(_data())
        with pytest.raises(ValueError, match="expects dtype float32"):
            k(np.zeros((M, N), np.int32))

    def test_wrong_arity_same_typeerror(self):
        k = tilelang.compile(_scale_func(2.5))
        a = _data()
        with pytest.raises(TypeError, match="expected 1 input tensors"):
            k(a, a, a)

    def test_reference_style_out_buffer_still_works(self):
        k = tilelang.compile(_scale_func(2.5))
        a = _data()
        out = np.zeros((M, N), np.float32)
        assert k(a, out) is None
        np.testing.assert_allclose(out, a * 2.5, rtol=1e-6)

    def test_env_flags_rearm_on_change(self, monkeypatch):
        """The plan's cached flags re-derive when a watched env var
        changes mid-process — metrics flipped on start recording on
        the very next call."""
        k = tilelang.compile(_scale_func(2.5))
        a = _data()
        k(a); k(a)
        assert _hist.get_histogram(OVERHEAD_HIST,
                                   kernel=k.artifact.name,
                                   path="fast") is None
        monkeypatch.setenv("TL_TPU_RUNTIME_METRICS", "1")
        k(a)
        h = _hist.get_histogram(OVERHEAD_HIST, kernel=k.artifact.name,
                                path="fast")
        assert h is not None and h.count == 1
        assert _hist.get_histogram(HIST_NAME, kernel=k.artifact.name,
                                   source="dispatch").count == 1
        monkeypatch.delenv("TL_TPU_RUNTIME_METRICS")
        k(a)
        assert h.count == 1   # recording stopped again


# ---------------------------------------------------------------------------
# buffer donation (TL_TPU_DONATE)
# ---------------------------------------------------------------------------

class TestDonation:
    def test_jax_inout_input_donated(self):
        import jax.numpy as jnp
        k = tilelang.compile(_bump_func())
        assert k._plan.donate_argnums == (0,)
        k(jnp.zeros((M, N), jnp.float32))       # cold: no donation
        x = jnp.zeros((M, N), jnp.float32)
        r = k(x)                                 # warm: donated
        np.testing.assert_allclose(np.asarray(r), 1.0)
        assert x.is_deleted()
        with pytest.raises(RuntimeError, match="deleted"):
            (x + 1).block_until_ready()

    def test_numpy_caller_not_donated_gets_copy_back(self):
        k = tilelang.compile(_bump_func())
        a = np.zeros((M, N), np.float32)
        assert k(a) is None      # cold: copy-back convention
        assert k(a) is None      # warm: still copy-back, never donates
        np.testing.assert_allclose(a, 2.0)

    def test_donate_env_bypass(self, monkeypatch):
        import jax.numpy as jnp
        monkeypatch.setenv("TL_TPU_DONATE", "0")
        k = tilelang.compile(_bump_func())
        k(jnp.zeros((M, N), jnp.float32))
        x = jnp.zeros((M, N), jnp.float32)
        r = k(x)
        np.testing.assert_allclose(np.asarray(r), 1.0)
        assert not x.is_deleted()
        np.testing.assert_allclose(np.asarray(x), 0.0)   # caller keeps it

    def test_donation_results_equal_non_donated(self):
        import jax.numpy as jnp
        k = tilelang.compile(_bump_func())
        a = _data()
        k(jnp.asarray(a))                        # cold
        donated = np.asarray(k(jnp.asarray(a)))  # warm, donated
        plain = np.asarray(a) + 1.0
        np.testing.assert_allclose(donated, plain, rtol=1e-6)


# ---------------------------------------------------------------------------
# dlpack round trips (utils/tensor.py satellites)
# ---------------------------------------------------------------------------

class TestZeroCopyIO:
    def test_numpy_roundtrip(self):
        a = _data()
        j = to_jax(a)
        np.testing.assert_array_equal(np.asarray(j), a)

    def test_numpy_noncontiguous_falls_back(self):
        base = np.arange(24, dtype=np.float32).reshape(4, 6)
        view = base.T                       # non-contiguous
        j = to_jax(view)
        np.testing.assert_array_equal(np.asarray(j), view)

    def test_torch_roundtrip_via_dlpack(self):
        torch = pytest.importorskip("torch")
        t = torch.arange(12, dtype=torch.float32).reshape(3, 4)
        j = to_jax(t)
        np.testing.assert_array_equal(np.asarray(j), t.numpy())

    def test_torch_noncontiguous(self):
        torch = pytest.importorskip("torch")
        t = torch.arange(12, dtype=torch.float32).reshape(3, 4).t()
        j = to_jax(t)
        np.testing.assert_array_equal(np.asarray(j), t.contiguous().numpy())

    def test_torch_bfloat16_roundtrip(self):
        """bfloat16 cannot pass through numpy at all — dlpack is the
        only route (the pre-PR detach().numpy() path raised)."""
        torch = pytest.importorskip("torch")
        import jax.numpy as jnp
        t = torch.arange(8, dtype=torch.bfloat16)
        j = to_jax(t)
        assert j.dtype == jnp.bfloat16
        dst = torch.zeros(8, dtype=torch.bfloat16)
        copy_back(dst, j)
        assert torch.equal(dst, t)

    def test_torch_requires_grad_detached(self):
        torch = pytest.importorskip("torch")
        t = torch.ones(4, requires_grad=True)
        j = to_jax(t)
        np.testing.assert_array_equal(np.asarray(j), np.ones(4, np.float32))

    def test_copy_back_numpy(self):
        import jax.numpy as jnp
        src = jnp.asarray(_data())
        dst = np.zeros((M, N), np.float32)
        copy_back(dst, src)
        np.testing.assert_array_equal(dst, np.asarray(src))

    def test_copy_back_torch(self):
        torch = pytest.importorskip("torch")
        import jax.numpy as jnp
        src = jnp.asarray(_data())
        dst = torch.zeros((M, N), dtype=torch.float32)
        copy_back(dst, src)
        np.testing.assert_array_equal(dst.numpy(), np.asarray(src))

    def test_gpu_torch_rejected(self):
        torch = pytest.importorskip("torch")
        if torch.cuda.is_available():   # pragma: no cover - CPU CI
            t = torch.ones(4, device="cuda")
            with pytest.raises(ValueError, match="CPU torch"):
                to_jax(t)

    def test_kernel_accepts_torch_inputs(self):
        torch = pytest.importorskip("torch")
        k = tilelang.compile(_scale_func(2.5))
        a = _data()
        r = np.asarray(k(torch.from_numpy(a.copy())))
        np.testing.assert_allclose(r, a * 2.5, rtol=1e-6)
        # warm path too
        r2 = np.asarray(k(torch.from_numpy(a.copy())))
        np.testing.assert_array_equal(r, r2)


# ---------------------------------------------------------------------------
# dispatch.overhead histogram + summaries
# ---------------------------------------------------------------------------

class TestOverheadInstrumentation:
    def test_fast_and_legacy_paths_recorded(self, monkeypatch):
        k = tilelang.compile(_scale_func(2.5))
        a = _data()
        k(a); k(a)
        monkeypatch.setenv("TL_TPU_RUNTIME_METRICS", "1")
        for _ in range(5):
            k(a)
        monkeypatch.setenv("TL_TPU_FAST_DISPATCH", "0")
        for _ in range(5):
            k(a)
        name = k.artifact.name
        hf = _hist.get_histogram(OVERHEAD_HIST, kernel=name, path="fast")
        hl = _hist.get_histogram(OVERHEAD_HIST, kernel=name, path="legacy")
        assert hf.count == 5 and hl.count == 5
        assert hf.quantile(0.5) > 0 and hl.quantile(0.5) > 0

    def test_runtime_summary_carries_overhead(self, monkeypatch):
        k = tilelang.compile(_scale_func(2.5))
        a = _data()
        k(a); k(a)
        monkeypatch.setenv("TL_TPU_RUNTIME_METRICS", "1")
        for _ in range(4):
            k(a)
        rt = metrics_summary()["runtime"][k.artifact.name]
        assert rt["count"] == 4
        assert rt["host_overhead_p50_us"] > 0
        assert rt["host_overhead_by_path"]["fast"] > 0

    def test_profiler_dispatch_overhead(self):
        k = tilelang.compile(_scale_func(2.5))
        prof = k.get_profiler()
        d = prof.dispatch_overhead(calls=20, warmup=2)
        assert d["path"] == "fast"
        assert d["overhead_samples"] == 20
        assert d["overhead_p50_us"] > 0
        assert d["calls_per_sec"] > 0

    def test_histogram_minus(self):
        from tilelang_mesh_tpu.observability import Histogram
        h = Histogram()
        for v in (1e-5, 2e-5, 4e-5):
            h.observe(v)
        snap = h.minus(None)
        for v in (1e-3, 2e-3):
            h.observe(v)
        delta = h.minus(snap)
        assert delta.count == 2
        assert delta.quantile(0.5) > 5e-4   # only the new observations

    def test_analyzer_trace_runtime_section(self, monkeypatch, tmp_path):
        from tilelang_mesh_tpu.observability import write_jsonl, read_jsonl
        from tilelang_mesh_tpu.tools.analyzer import (format_trace_report,
                                                      summarize_trace)
        k = tilelang.compile(_scale_func(2.5))
        a = _data()
        k(a); k(a)
        monkeypatch.setenv("TL_TPU_RUNTIME_METRICS", "1")
        for _ in range(3):
            k(a)
        p = write_jsonl(tmp_path / "t.jsonl")
        records = read_jsonl(p)
        rt = summarize_trace(records)["runtime"]
        d = rt[k.artifact.name]
        assert d["calls"] == 3
        assert d["host_overhead_by_path"]["fast"] > 0
        report = format_trace_report(records)
        assert "host_overhead_p50" in report


# ---------------------------------------------------------------------------
# sanitizer + failover interplay through the fast path
# ---------------------------------------------------------------------------

class TestGuardInterplay:
    def test_sanitizer_fires_through_fast_path(self, monkeypatch):
        from tilelang_mesh_tpu.verify import NumericError

        @T.prim_func
        def div(A: T.Tensor((M, N), "float32"),
                B: T.Tensor((M, N), "float32")):
            with T.Kernel(1) as bx:
                s = T.alloc_shared((M, N), "float32")
                T.copy(A, s)
                for i, j in T.Parallel(M, N):
                    s[i, j] = s[i, j] / 0.0
                T.copy(s, B)

        k = tilelang.compile(div)
        a = np.ones((M, N), np.float32)
        k(a)   # warm, sanitizer off: Inf flows through silently
        monkeypatch.setenv("TL_TPU_SANITIZE", "1")
        with pytest.raises(NumericError):
            k(a)

    def test_warm_device_loss_fails_over_through_fast_path(
            self, monkeypatch):
        monkeypatch.setenv("TL_TPU_BACKENDS", "host-xla,host-interpret")
        from tilelang_mesh_tpu.codegen.backends import registry
        registry().reset()
        k = tilelang.compile(_scale_func(1.5))
        a = _data()
        np.testing.assert_allclose(np.asarray(k(a)), a * 1.5, rtol=1e-6)
        assert k.backend == "host-xla"
        with inject("device.dispatch", kind="unreachable", times=1):
            np.testing.assert_allclose(np.asarray(k(a)), a * 1.5,
                                       rtol=1e-6)
        assert k.backend == "host-interpret"
        # the plan's closure now drives the re-lowered backend
        np.testing.assert_allclose(np.asarray(k(a)), a * 1.5, rtol=1e-6)

    def test_failover_rearms_donation_variant(self, monkeypatch):
        import jax.numpy as jnp
        monkeypatch.setenv("TL_TPU_BACKENDS", "host-xla,host-interpret")
        from tilelang_mesh_tpu.codegen.backends import registry
        registry().reset()
        k = tilelang.compile(_bump_func())
        k(jnp.zeros((M, N), jnp.float32))                 # cold
        k(jnp.zeros((M, N), jnp.float32))                 # warm: donates
        assert k._plan._donate_cache is not None
        with inject("device.dispatch", kind="unreachable", times=1):
            k(jnp.zeros((M, N), jnp.float32))
        # the failover dropped the stale donation variant; the next
        # donated call re-jits against the new backend and still works
        assert k.backend == "host-interpret"
        x = jnp.zeros((M, N), jnp.float32)
        np.testing.assert_allclose(np.asarray(k(x)), 1.0)
        assert x.is_deleted()

    def test_mesh_overhead_recorded(self, monkeypatch):
        """MeshKernel's hoisted marshalling records into the shared
        overhead histogram under path=mesh."""
        import jax
        if len(jax.devices()) < 4:
            pytest.skip("needs 4 virtual devices")
        import jax.numpy as jnp
        from tilelang_mesh_tpu.parallel import mesh_config
        rows = cols = 2
        n, m = 16, 128
        mesh_t = (rows, cols)
        shard = T.MeshShardingPolicy(cross_mesh_dim=0)
        with mesh_config(rows, cols):
            @T.prim_func
            def ksum(A: T.MeshTensor((rows * cols * n, m), shard, mesh_t,
                                     "float32"),
                     B: T.MeshTensor((rows * cols * n, 1), shard, mesh_t,
                                     "float32")):
                with T.Kernel(1) as bx:
                    x = T.alloc_fragment((n, m), "float32")
                    o = T.alloc_fragment((n, 1), "float32")
                    T.copy(A, x)
                    T.comm.all_reduce(x, o, "sum", "all", dim=1)
                    T.copy(o, B)
            kern = tilelang.compile(
                ksum, target=f"cpu-mesh[{rows}x{cols}]")
        a = jnp.asarray(np.random.default_rng(0).standard_normal(
            (rows * cols * n, m)) * 0.1, jnp.float32)
        kern(a)   # cold (trace+compile)
        monkeypatch.setenv("TL_TPU_RUNTIME_METRICS", "1")
        kern(a)
        h = _hist.get_histogram(OVERHEAD_HIST, kernel=kern.artifact.name,
                                path="mesh")
        assert h is not None and h.count == 1
