"""Block-sparse attention vs dense flash latency — mirror of the
reference's benchmark/blocksparse_attention scripts (dense/triton/torch
comparisons; here block-sparse vs dense tile kernels on TPU).

Run: python benchmark/blocksparse_attention/benchmark_blocksparse.py
"""

import argparse
import sys

import numpy as np


def main():
    import jax.numpy as jnp
    sys.path.insert(0, ".")
    from bench import _time_fn
    from tilelang_mesh_tpu.ops.blocksparse_attention import (
        blocksparse_mha_kernel)
    from tilelang_mesh_tpu.ops.flash_attention import mha_fwd_kernel

    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()

    B, H, D = 1, 8, 64
    BM = BN = 128
    seqs = (1024,) if args.quick else (1024, 2048, 4096)
    rng = np.random.default_rng(0)
    print("| seq | density | sparse ms | dense ms | speedup |")
    print("|---|---|---|---|---|")
    for S in seqs:
        nb = S // BM
        q = jnp.asarray(rng.standard_normal((B, H, S, D)) * 0.3,
                        jnp.bfloat16)
        k = jnp.asarray(rng.standard_normal((B, H, S, D)) * 0.3,
                        jnp.bfloat16)
        v = jnp.asarray(rng.standard_normal((B, H, S, D)) * 0.3,
                        jnp.bfloat16)
        # causal-band mask at ~25% density: diagonal + previous block
        mask = np.zeros((B, H, nb, nb), np.bool_)
        for i in range(nb):
            mask[:, :, i, max(0, i - 1):i + 1] = True
        dense = mha_fwd_kernel(B, H, S, S, D, causal=True,
                               dtype="bfloat16")
        sparse = blocksparse_mha_kernel(B, H, S, S, D, BM, BN,
                                        1.0 / D ** 0.5, "bfloat16",
                                        causal=True)
        dt_d = _time_fn(dense.func, (q, k, v), rep=10)
        dt_s = _time_fn(sparse.func, (q, k, v, jnp.asarray(mask)), rep=10)
        dens = mask.sum() / mask.size
        print(f"| {S} | {dens:.2f} | {dt_s * 1e3:.3f} | {dt_d * 1e3:.3f} "
              f"| {dt_d / dt_s:.2f}x |")


if __name__ == "__main__":
    main()
