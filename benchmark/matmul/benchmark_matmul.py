"""bf16 GEMM throughput sweep — mirror of the reference's headline table
(/root/reference/benchmark/matmul: 8192x8192xK for K in 256..16384).

Run on TPU: python benchmark/matmul/benchmark_matmul.py [--quick]
Prints a markdown table of TFLOPS per K plus the hand-written-Pallas ratio.
"""

import argparse
import sys

import numpy as np


def bench_shape(M, N, K, configs, rep=20):
    import jax.numpy as jnp
    sys.path.insert(0, ".")
    from bench import _time_fn, _hand_pallas_matmul
    from tilelang_mesh_tpu.ops.gemm import matmul_kernel

    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.standard_normal((M, K)) * 0.1, jnp.bfloat16)
    b = jnp.asarray(rng.standard_normal((K, N)) * 0.1, jnp.bfloat16)
    flops = 2.0 * M * N * K

    best_ours, best_ref = None, None
    for cfg in configs:
        try:
            k = matmul_kernel(M, N, K, in_dtype="bfloat16", **cfg)
            dt = _time_fn(k.func, (a, b), rep=rep)
            best_ours = dt if best_ours is None else min(best_ours, dt)
        except Exception as e:
            print(f"# ours {cfg}: {e}", file=sys.stderr)
        try:
            ref = _hand_pallas_matmul(M, N, K, cfg["block_M"],
                                      cfg["block_N"], cfg["block_K"])
            dt = _time_fn(ref, (a, b), rep=rep)
            best_ref = dt if best_ref is None else min(best_ref, dt)
        except Exception as e:
            print(f"# ref {cfg}: {e}", file=sys.stderr)
    ours = flops / best_ours / 1e12 if best_ours else float("nan")
    refv = flops / best_ref / 1e12 if best_ref else float("nan")
    return ours, refv


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--mn", type=int, default=8192)
    args = ap.parse_args()

    M = N = args.mn
    ks = (256, 1024, 4096) if args.quick else (256, 512, 1024, 2048, 4096,
                                               8192, 16384)
    configs = [{"block_M": 256, "block_N": 256, "block_K": 512},
               {"block_M": 512, "block_N": 256, "block_K": 256},
               {"block_M": 256, "block_N": 512, "block_K": 512}]
    print(f"| K | tile-DSL TFLOPS | hand-Pallas TFLOPS | ratio |")
    print(f"|---|---|---|---|")
    for K in ks:
        cfgs = [c for c in configs if c["block_K"] <= K] or \
            [{"block_M": 256, "block_N": 256, "block_K": K}]
        ours, ref = bench_shape(M, N, K, cfgs)
        print(f"| {K} | {ours:.1f} | {ref:.1f} | {ours / ref:.3f} |")


if __name__ == "__main__":
    main()
