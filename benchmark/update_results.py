"""Regenerate the measured-results table in benchmark/RESULTS.md from
bench.py JSON lines.

Usage:
    python bench.py | tee /tmp/bench.jsonl
    python benchmark/update_results.py /tmp/bench.jsonl [--date 2026-07-30]

Only rows present in the input are updated; other rows keep their
existing (dated) values, so partial sweeps refresh incrementally. The
table is rewritten in place between the BEGIN/END markers; everything
else in RESULTS.md is untouched.
"""

import argparse
import datetime
import json
import pathlib
import re
import sys

RESULTS = pathlib.Path(__file__).resolve().parent / "RESULTS.md"
BEGIN = "<!-- BENCH_TABLE_BEGIN -->"
END = "<!-- BENCH_TABLE_END -->"

def _config_order():
    """The sweep order, derived from bench.py itself (no drift): any
    config bench can emit has a slot, in bench's own risk ordering."""
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
    import bench
    return [n for n, _ in bench._config_builders(False)]


def parse_lines(path):
    recs = {}
    for line in pathlib.Path(path).read_text().splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            r = json.loads(line)
        except json.JSONDecodeError:
            continue
        if "config" in r and "value" in r and "error" not in r:
            recs[r["config"]] = r
    return recs


def fmt_row(name, r, date):
    vs = r["vs_baseline"]
    vs_s = f"**{vs:.3f}**" if vs >= 1.0 else f"{vs:.3f}"
    # walk_ms / gather_ms are each OMITTED when that candidate failed
    # (bench.py cfg_paged_decode), so render whichever keys exist
    extra = "".join(f" {label}={r[key]}ms"
                    for label, key in (("walk", "walk_ms"),
                                       ("gather", "gather_ms"))
                    if key in r)
    return (f"| {name} | {r['metric']}{extra} | {r['value']} {r['unit']} | "
            f"{r['latency_ms']} | {r['baseline_ms']} | {vs_s} | {date} |")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("jsonl")
    ap.add_argument("--date",
                    default=datetime.date.today().isoformat())
    args = ap.parse_args()

    recs = parse_lines(args.jsonl)
    if not recs:
        print("no valid bench records found", file=sys.stderr)
        sys.exit(1)

    text = RESULTS.read_text()
    if BEGIN not in text or END not in text:
        print(f"{RESULTS} lacks {BEGIN} / {END} markers", file=sys.stderr)
        sys.exit(1)

    order = _config_order()
    # any row already in the table stays even if bench.py no longer
    # lists it (renamed configs keep their history visible)
    block = text.split(BEGIN)[1].split(END)[0]
    existing = {}
    for line in block.splitlines():
        m = re.match(r"\|\s*(\w+)\s*\|", line)
        if m and m.group(1) != "config":
            existing[m.group(1)] = line
    order += [n for n in existing if n not in order]

    rows = []
    for name in order:
        if name in recs:
            rows.append(fmt_row(name, recs[name], args.date))
        elif name in existing:
            rows.append(existing[name])

    header = ("| config | metric | value | ours ms | baseline ms | "
              "vs_baseline | measured |\n|---|---|---|---|---|---|---|")
    new_block = f"\n{header}\n" + "\n".join(rows) + "\n"
    text = text.split(BEGIN)[0] + BEGIN + new_block + END + \
        text.split(END)[1]
    RESULTS.write_text(text)
    n_new = len([n for n in order if n in recs])
    print(f"updated {n_new} rows ({args.date}); "
          f"kept {len(rows) - n_new} existing")


if __name__ == "__main__":
    main()
