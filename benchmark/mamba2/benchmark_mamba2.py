"""Mamba2 chunk-scan benchmark (reference benchmark/mamba2/README table:
b=8, h=80, chunk=256, d=64, dstate=128, seq 1k..8k)."""

import argparse
import sys

import numpy as np


def main():
    import jax.numpy as jnp
    sys.path.insert(0, ".")
    from bench import _time_fn
    from tilelang_mesh_tpu.ops.mamba2 import mamba2_chunk_scan_kernel

    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--heads", type=int, default=80)
    args = ap.parse_args()

    B, H, P, N, chunk = args.batch, args.heads, 64, 128, 256
    seqs = (1024,) if args.quick else (1024, 2048, 4096, 8192)
    rng = np.random.default_rng(0)
    print("| seq | latency ms | TFLOPS |")
    print("|---|---|---|")
    for S in seqs:
        kern = mamba2_chunk_scan_kernel(B, S, H, P, N, chunk, "bfloat16")
        x = jnp.asarray(rng.standard_normal((B, H, S, P)) * 0.3,
                        jnp.bfloat16)
        dt = jnp.asarray(rng.uniform(0.01, 0.1, (B, H, S)), jnp.float32)
        A = jnp.asarray(-rng.uniform(0.5, 1.5, (H,)), jnp.float32)
        Bm = jnp.asarray(rng.standard_normal((B, S, N)) * 0.3, jnp.bfloat16)
        Cm = jnp.asarray(rng.standard_normal((B, S, N)) * 0.3, jnp.bfloat16)
        t = _time_fn(kern.func, (x, dt, A, Bm, Cm), rep=10)
        # FLOPs: per chunk: CB^T (Q^2 N) + attn@X (Q^2 P) + C@state (Q N P)
        # + state update (Q N P), x2 for MAC
        nc = S // chunk
        flops = 2.0 * B * H * nc * (chunk * chunk * N + chunk * chunk * P +
                                    2 * chunk * N * P)
        print(f"| {S} | {t * 1e3:.3f} | {flops / t / 1e12:.1f} |")


if __name__ == "__main__":
    main()
