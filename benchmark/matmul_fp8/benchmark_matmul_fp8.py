"""fp8 GEMM throughput sweep — mirror of the reference's
benchmark/matmul_fp8 table (8192x8192xK sweeps on H800; here e4m3 through
the tile pipeline on the local TPU).

Run: python benchmark/matmul_fp8/benchmark_matmul_fp8.py [--quick]
"""

import argparse
import sys

import numpy as np


def main():
    import jax.numpy as jnp
    sys.path.insert(0, ".")
    from bench import _time_fn
    from tilelang_mesh_tpu.ops.gemm import matmul_kernel

    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--mn", type=int, default=2048)
    args = ap.parse_args()

    M = N = args.mn
    ks = (512, 1024) if args.quick else (256, 512, 1024, 2048, 4096)
    rng = np.random.default_rng(0)
    print(f"| M=N={M} | K | latency ms | TFLOPS |")
    print("|---|---|---|---|")
    for K in ks:
        a = jnp.asarray(rng.standard_normal((M, K)) * 0.1,
                        jnp.float8_e4m3fn)
        b = jnp.asarray(rng.standard_normal((K, N)) * 0.1,
                        jnp.float8_e4m3fn)
        best = None
        for cfg in ({"block_M": 256, "block_N": 256, "block_K": 512},
                    {"block_M": 512, "block_N": 256, "block_K": 256}):
            try:
                kern = matmul_kernel(M, N, K, in_dtype="float8_e4m3fn",
                                     out_dtype="float32", **cfg)
                dt = _time_fn(kern.func, (a, b), rep=20)
                best = dt if best is None else min(best, dt)
            except Exception as e:
                print(f"# cfg {cfg} failed: {e}", file=sys.stderr)
        if best is not None:
            fl = 2.0 * M * N * K
            print(f"| {M} | {K} | {best * 1e3:.3f} | "
                  f"{fl / best / 1e12:.1f} |")


if __name__ == "__main__":
    main()
