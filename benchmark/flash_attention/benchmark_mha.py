"""FlashAttention fwd latency/throughput (reference
examples/flash_attention/README benchmark behavior; BASELINE config #2)."""

import argparse
import sys

import numpy as np


def main():
    import jax.numpy as jnp
    sys.path.insert(0, ".")
    from bench import _time_fn
    from tilelang_mesh_tpu.ops.flash_attention import mha_fwd_kernel

    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()

    B, H = 1, 16
    cases = [(1024, 64), (1024, 128)] if args.quick else \
        [(1024, 64), (2048, 64), (4096, 64), (1024, 128), (2048, 128),
         (4096, 128)]
    print("| seq | head_dim | causal | latency ms | TFLOPS |")
    print("|---|---|---|---|---|")
    rng = np.random.default_rng(0)
    for S, D in cases:
        for causal in (False, True):
            q = jnp.asarray(rng.standard_normal((B, H, S, D)) * 0.3,
                            jnp.bfloat16)
            k = jnp.asarray(rng.standard_normal((B, H, S, D)) * 0.3,
                            jnp.bfloat16)
            v = jnp.asarray(rng.standard_normal((B, H, S, D)) * 0.3,
                            jnp.bfloat16)
            kern = mha_fwd_kernel(B, H, S, S, D, causal=causal,
                                  dtype="bfloat16")
            dt = _time_fn(kern.func, (q, k, v), rep=20)
            flops = 4.0 * B * H * S * S * D * (0.5 if causal else 1.0)
            print(f"| {S} | {D} | {causal} | {dt * 1e3:.3f} | "
                  f"{flops / dt / 1e12:.1f} |")


if __name__ == "__main__":
    main()
