"""Roofline verdicts for the headline bench configs (VERDICT r3 #6).

For each measured config: per-resource roofline times (MXU / HBM / VPU)
from the carver arch model at the MEASURED tile config, the binding
resource, and the attained fraction vs that roofline. Pure arithmetic —
no device needed; measured latencies are the committed RESULTS.md rows.

Run: python benchmark/roofline.py   (prints the markdown table)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tilelang_mesh_tpu.carver.arch import TPU_V5E  # noqa: E402

_VPU_ELEMS_PER_S = 0.5e12   # carver roller model constant (conservative)


def _measured_ms():
    """Latest committed latencies, read from the RESULTS.md table (the
    same rows benchmark/update_results.py regenerates, via its own
    markers) — the roofline stays consistent with every fresh sweep."""
    import importlib.util
    import pathlib
    import re
    here = pathlib.Path(__file__).resolve().parent
    spec = importlib.util.spec_from_file_location(
        "_ur", here / "update_results.py")
    ur = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ur)
    text = (here / "RESULTS.md").read_text()
    if ur.BEGIN not in text or ur.END not in text:
        raise SystemExit(f"RESULTS.md lacks {ur.BEGIN} / {ur.END}")
    block = text.split(ur.BEGIN)[1].split(ur.END)[0]
    header = next(l for l in block.splitlines() if "| config |" in l)
    ours_col = [c.strip() for c in header.strip().strip("|")
                .split("|")].index("ours ms")
    out = {}
    for line in block.splitlines():
        cells = [c.strip() for c in line.strip().strip("|").split("|")]
        if len(cells) > ours_col and re.match(r"^\w+$", cells[0]) \
                and cells[0] != "config":
            try:
                out[cells[0]] = float(cells[ours_col])
            except ValueError:
                pass
    return out


def _meas(meas, name):
    """Loud lookup: a renamed/dropped config must not become a silent
    NaN row."""
    if name not in meas:
        import sys
        print(f"# roofline: {name} missing from RESULTS.md table",
              file=sys.stderr)
        return float("nan")
    return meas[name]


def _roofline(name, flops, hbm_bytes, vpu_elems, measured_ms, note="",
              peak_tflops=None):
    arch = TPU_V5E
    peak = (peak_tflops or arch.bf16_tflops) * 1e12
    t_mxu = flops / peak * 1e3
    t_hbm = hbm_bytes / (arch.hbm_gbps * 1e9) * 1e3
    t_vpu = vpu_elems / _VPU_ELEMS_PER_S * 1e3
    times = {"MXU": t_mxu, "HBM": t_hbm, "VPU": t_vpu}
    bound = max(times, key=times.get)
    roof = times[bound]
    attained = roof / measured_ms if measured_ms else float("nan")
    implied_vpu = (vpu_elems / (measured_ms * 1e-3) / 1e12
                   if vpu_elems else 0.0)
    return dict(name=name, t_mxu=t_mxu, t_hbm=t_hbm, t_vpu=t_vpu,
                bound=bound, roof=roof, measured=measured_ms,
                attained=attained, implied_vpu=implied_vpu, note=note)


def rows():
    meas = _measured_ms()
    out = []
    # gemm_large: 8192x8192x4096 bf16
    M, N, K = 8192, 8192, 4096
    bm, bn = 512, 1024   # measured winning tile class (carver rank-1)
    out.append(_roofline(
        "gemm_large", 2.0 * M * N * K,
        (M * K * (N // bn) + K * N * (M // bm)) * 2 + M * N * 2,
        0, _meas(meas, "gemm_large")))
    # flash_d64: B=2 H=16 S=2048 d=64 causal,
    # carver FlashAttentionTemplate accounting: 8 VPU elem-ops per score
    BH, S, D, frac = 32, 2048, 64, 0.5
    n_q = S // 256
    out.append(_roofline(
        "flash_d64", 4.0 * BH * S * S * D * frac,
        BH * (S * D * 2 + 2 * S * D * 2 * n_q * frac + S * D * 2),
        BH * S * S * frac * 8, _meas(meas, "flash_d64"),
        note="softmax VPU work dominates at d=64"))
    # flash_d128
    D = 128
    out.append(_roofline(
        "flash_d128", 4.0 * BH * S * S * D * frac,
        BH * (S * D * 2 + 2 * S * D * 2 * n_q * frac + S * D * 2),
        BH * S * S * frac * 8, _meas(meas, "flash_d128")))
    # flash_d128_full (non-causal)
    out.append(_roofline(
        "flash_d128_full", 4.0 * BH * S * S * D,
        BH * (S * D * 2 + 2 * S * D * 2 * n_q + S * D * 2),
        BH * S * S * 8, _meas(meas, "flash_d128_full")))
    # w4a16 two-pass: dequant pass (rw 8MB+33MB) + 4096^3 GEMM
    M = N = K = 4096
    bm = bn = 1024
    dq_bytes = K // 2 * N + 2 * K * N   # packed read + bf16 write
    mm_bytes = (M * K * (N // bn) + K * N * (M // bm)) * 2 + M * N * 2 \
        + 2 * K * N                      # + dequantized-B read
    out.append(_roofline(
        "w4a16_gemm", 2.0 * M * N * K, dq_bytes + mm_bytes,
        K // 2 * N * 2, _meas(meas, "w4a16_gemm"),
        note="two-pass: VPU decode is O(KN) once"))
    # moe_grouped: E=8 per-expert 512x2048x2048
    E, M, K, N = 8, 512, 2048, 2048
    bm, bn = 512, 2048
    out.append(_roofline(
        "moe_grouped", 2.0 * E * M * K * N,
        E * ((M * K * (N // bn) + K * N * (M // bm)) * 2 + M * N * 2),
        0, _meas(meas, "moe_grouped")))
    # round-5 families (rows go live with their first measured sweep;
    # _meas prints a note and yields NaN until then)
    # mamba2: B=8 S=4096 H=80 P=64 N=128, chunk 256 — reference README
    # FLOPs formula; HBM = x/y r+w (bf16) + B/C reads; VPU ~ the decay
    # matrix + exps per chunk (C^2 per (b,h,chunk) f32 elems)
    Bm_, S_, H_, P_, N_, C_ = 8, 4096, 80, 64, 128, 256
    out.append(_roofline(
        "mamba2_chunk",
        2.0 * Bm_ * S_ * C_ * H_ * P_ * 0.5 + 2.0 * Bm_ * S_ * H_ * P_ * N_,
        Bm_ * S_ * (2 * H_ * P_ * 2 + 2 * N_ * 2),
        Bm_ * H_ * (S_ // C_) * C_ * C_ * 2,
        _meas(meas, "mamba2_chunk")))
    # gdn: B=8 H=16 T=4096 K=V=128, chunk 64 (bench formula; VPU ~ two
    # decay-masked C x C passes per chunk)
    Bg, Hg, Tg, Kg, Vg, Cg = 8, 16, 4096, 128, 128, 64
    out.append(_roofline(
        "gdn_fwd", Bg * Hg * Tg * (Cg * (Kg + Vg) + 6.0 * Kg * Vg),
        Bg * Hg * Tg * (2 * Kg + 2 * Vg) * 2,
        Bg * Hg * (Tg // Cg) * Cg * Cg * 2,
        _meas(meas, "gdn_fwd")))
    # w4a8 4096^3 on the int8 MXU path (peak = i8 rate); HBM = int8 A
    # per N-tile + packed int4 B per M-tile + f32 C
    M = N = K = 4096
    bm, bn = 256, 512
    out.append(_roofline(
        "w4a8_gemm", 2.0 * M * N * K,
        M * K * (N // bn) + K // 2 * N * (M // bm) + M * N * 4,
        # FUSED kernel: the B tile is re-decoded inside every M-tile's
        # K loop (unlike the two-pass w4a16 row's once-only decode)
        (M // bm) * K * N, _meas(meas, "w4a8_gemm"),
        peak_tflops=2 * TPU_V5E.bf16_tflops,
        note="int8 MXU path (2x bf16 peak); fused per-tile decode "
             "makes the model VPU-bound — sweeps may prefer larger bm"))
    return out


def main():
    print("| config | MXU ms | HBM ms | VPU ms (model) | bound | "
          "measured ms | attained vs roof | implied VPU Telem/s |")
    print("|---|---|---|---|---|---|---|---|")
    for r in rows():
        print(f"| {r['name']} | {r['t_mxu']:.3f} | {r['t_hbm']:.3f} | "
              f"{r['t_vpu']:.3f} | {r['bound']} | {r['measured']:.3f} | "
              f"{r['attained']:.2f}x | "
              f"{r['implied_vpu']:.2f} |")
    print()
    for r in rows():
        if r["note"]:
            print(f"- {r['name']}: {r['note']}")


if __name__ == "__main__":
    main()
